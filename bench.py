"""Headline benchmark: model-zoo training throughput (img/s) on one chip.

Baseline (BASELINE.md): MXNet v0.11 ResNet-50 ImageNet at batch 32 on one
K80 = 109 img/s (/root/reference/example/image-classification/README.md:147-157);
the NETWORKS table below carries every per-family K80 row from that README.
Default: gluon model_zoo ResNet-50 v1 compiled to one XLA program —
forward, softmax-CE loss, backward, SGD+momentum update — per step,
images 224x224x3.  BENCH_NETWORK selects any other family.

Timing methodology (round 3): the axon TPU tunnel's `block_until_ready`
returns before device completion, so a device→host fetch of the final
loss scalar is the only reliable completion barrier — every step's loss
depends on the previous step's (donated) params, so fetching the last
loss forces the whole chain.  Rounds 1-2 numbers (~2180 img/s at bs 256)
were dispatch-bound under-measurements; see PERF.md for the full analysis.

MFU is computed from the compiled step's XLA cost analysis against the
chip's nominal bf16 peak.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import functools
import json
import os
import sys
import threading
import time

# per-network reference baselines (1x K80 img/s) and fwd GMACs at 224²
# (299² for inception_v3) — reference example/image-classification/
# README.md:147-157,357; GMACs are the standard published counts
NETWORKS = {
    "resnet18_v1": (185.0, 1.82),
    "resnet34_v1": (172.0, 3.67),
    "resnet50_v1": (109.0, 4.089),
    "resnet101_v1": (78.0, 7.80),
    "resnet152_v1": (57.0, 11.51),
    "inception_v3": (30.0, 5.73),
    "alexnet": (457.0, 0.71),
    "vgg16": (None, 15.47),
    "densenet121": (None, 2.83),
    "squeezenet1_0": (None, 0.82),
}

_WATCHDOG_DONE = None  # set by _install_init_watchdog; modes disarm it


def _install_init_watchdog(metric="resnet50_train_images_per_sec",
                           unit="img/s"):
    """The axon tunnel can wedge hard: jax.devices() then blocks forever
    (observed mid-round-3, PERF.md §1 note).  A hung benchmark is worse
    than a failed one — if backend init doesn't complete in
    BENCH_INIT_TIMEOUT seconds, report the outage and exit nonzero."""
    timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "600"))
    if timeout <= 0:
        return None
    done = threading.Event()

    def _watch():
        if not done.wait(timeout):
            print(json.dumps({
                "metric": metric,
                "value": 0.0,
                "unit": "%s (measurement unavailable)" % unit,
                "vs_baseline": 0.0,
                "error": "TPU backend init timed out after %.0fs — "
                         "tunnel unavailable; see PERF.md §1 for the "
                         "last measured numbers and methodology"
                         % timeout,
            }), flush=True)
            os._exit(3)

    t = threading.Thread(target=_watch, daemon=True)
    t.start()
    global _WATCHDOG_DONE
    _WATCHDOG_DONE = done


def _network_metric(network):
    """'resnet50_v1' -> 'resnet50_train_images_per_sec' (the name the
    driver has tracked since round 1).  Only the '_v1' family default is
    stripped — 'inception_v3' keeps its version so the metric name
    round-trips to the BENCH_NETWORK value (ADVICE r3)."""
    if network.endswith("_v1"):
        network = network[:-3]
    return "%s_train_images_per_sec" % network


def _disarm_watchdog():
    """Call once the jax backend has answered — the hang risk is over."""
    if _WATCHDOG_DONE is not None:
        _WATCHDOG_DONE.set()

# nominal dense bf16 peak FLOP/s by device kind (for the MFU report)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def _perf_probe_path():
    """Put tools/perf_probe on sys.path once (steptrace/restart_probe
    imports for the probe-backed bench modes)."""
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools", "perf_probe")
    if d not in sys.path:
        sys.path.insert(0, d)


def _tier1_margin_gate():
    """Post-suite wall-margin assertion (ISSUE 17 satellite): with
    MXTPU_TIER1_LOG pointing at a captured tier-1 pytest log, the
    bench run refuses to pass when the suite overran the CI wall
    (MXTPU_TIER1_WALL, default 870 s) — the wall is discovered by this
    gate, never by the harness's kill.  Unset/missing log = skip: the
    gate only speaks when a suite actually ran."""
    path = os.environ.get("MXTPU_TIER1_LOG")
    if not path or not os.path.exists(path):
        return
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tools")
    if d not in sys.path:
        sys.path.insert(0, d)
    import tier1_margin
    wall = float(os.environ.get("MXTPU_TIER1_WALL", "870"))
    with open(path) as f:
        elapsed, m = tier1_margin.margin(f.read(), wall)
    if elapsed is None:
        print("tier1-margin: no pytest summary in %s — the suite "
              "died before reporting; failing the bench run" % path,
              file=sys.stderr, flush=True)
        sys.exit(5)
    print("tier1-margin: suite %.1fs, wall %.0fs, margin %+.1fs"
          % (elapsed, wall, m), file=sys.stderr, flush=True)
    if m < 0:
        print("tier1-margin: tier-1 OVERRAN the wall; failing the "
              "bench run", file=sys.stderr, flush=True)
        sys.exit(5)


def bench_attention():
    """BENCH_MODE=attention: Pallas flash-attention step vs chip peak.

    Times fwd+bwd of the fused kernel on [B,H,T,D] = (4, 16, 4096, 128)
    — ~O(T) memory where the einsum oracle would hold a 4096² score
    matrix per head.  Attention FLOPs: 4·B·H·T²·D per fwd, ×3.5 for
    fwd+bwd (dq, dk, dv re-use the two matmuls plus recompute).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    b, h, t, d = (int(os.environ.get("BENCH_ATTN_" + k, v)) for k, v in
                  (("B", 4), ("H", 16), ("T", 4096), ("D", 128)))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    platform = jax.devices()[0].platform
    _disarm_watchdog()
    device_kind = jax.devices()[0].device_kind
    if platform == "cpu":
        if "BENCH_ATTN_T" not in os.environ:
            t = 512
        if "BENCH_STEPS" not in os.environ:
            steps = 2

    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16 if platform != "cpu" else jnp.float32
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (b, h, t, d), dt) for i in range(3))

    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()
        l, (dq, dk, dv) = jax.value_and_grad(loss, argnums=(0, 1, 2))(
            q, k, v)
        # reduce grads to ONE scalar output: keeps the backward live
        # (returning l alone lets XLA dead-code-eliminate it) without
        # shipping 48 MB of gradient outputs through the device tunnel
        # every step, which dominates and destabilizes the measurement
        gs = (dq.astype(jnp.float32).sum() + dk.astype(jnp.float32).sum()
              + dv.astype(jnp.float32).sum())
        return l, gs

    l, gs = step(q, k, v)
    np.asarray(gs)                      # completion barrier (PERF.md §1)
    t0 = time.perf_counter()
    for _ in range(steps):
        l, gs = step(q, k, v)
    np.asarray(gs)
    dtime = time.perf_counter() - t0
    # causal halves the score matrix work
    flops = 3.5 * 4 * b * h * t * t * d / 2 * steps
    result = {
        "metric": "flash_attention_train_tflops",
        "value": round(flops / dtime / 1e12, 2),
        "unit": "TFLOP/s (B%d H%d T%d D%d causal %s fwd+bwd, 1 %s)"
                % (b, h, t, d, jnp.dtype(dt).name, platform),
        "vs_baseline": 0.0,  # no reference counterpart (2017, pre-attention)
        "ms_per_step": round(dtime / steps * 1e3, 2),
    }
    peak = PEAK_FLOPS.get(device_kind)
    if peak:
        result["mfu"] = round(flops / dtime / peak, 3)
    print(json.dumps(result))


GPT_CONFIGS = {"tiny": (2, 128, 4), "small": (12, 768, 12),
               "medium": (24, 1024, 16)}


def _gpt_metric(kind="train"):
    cfg_name = os.environ.get("BENCH_GPT", "small")
    if cfg_name not in GPT_CONFIGS:
        raise ValueError("BENCH_GPT must be one of %s, got %r"
                         % (sorted(GPT_CONFIGS), cfg_name))
    return cfg_name, "gpt2_%s_%s_tokens_per_sec" % (cfg_name, kind)


def bench_generate():
    """BENCH_MODE=generate: GPT flagship INFERENCE throughput.

    Times gpt.generate (prefill + jitted KV-cache decode scan): one
    batched causal pass over the prompt, then n_new sequential decode
    steps.  Metric is decoded tokens/s (batch * n_new / wall) with the
    prompt prefill amortized in — the serving-path number next to the
    training MFU headline.
    """
    import numpy as np
    import jax

    cfg_name, metric = _gpt_metric("generate")
    n_layer, d_model, n_head = GPT_CONFIGS[cfg_name]
    platform = jax.devices()[0].platform
    _disarm_watchdog()
    device_kind = jax.devices()[0].device_kind
    on_cpu = platform == "cpu"
    prompt_len = int(os.environ.get("BENCH_PROMPT", "32" if on_cpu
                                    else "512"))
    n_new = int(os.environ.get("BENCH_NEW", "16" if on_cpu else "128"))
    batch = int(os.environ.get("BENCH_BATCH", "2" if on_cpu else "8"))
    steps = max(1, int(os.environ.get("BENCH_STEPS",
                                      "2" if on_cpu else "10")))
    vocab = 512 if on_cpu else 50304

    from mxnet_tpu.gluon.model_zoo import gpt
    net = gpt.GPTLM(vocab, n_layer, d_model, n_head,
                    max_len=prompt_len + n_new)
    net.initialize()
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, vocab, (batch, prompt_len)).astype(np.int32)

    # warm up the SAME (sampling) runner the timed loop uses — greedy
    # and sampling compile different scans (static cache key)
    gpt.generate(net, prompt, n_new, temperature=0.8, seed=-1)
    t0 = time.perf_counter()
    for i in range(steps):
        out = gpt.generate(net, prompt, n_new, temperature=0.8,
                           seed=i)
    dt = (time.perf_counter() - t0) / steps
    assert out.shape == (batch, prompt_len + n_new)
    tok_s = batch * n_new / dt
    print(json.dumps({
        "metric": metric,
        "value": round(tok_s, 1),
        "unit": "tok/s (B%d prompt %d +%d new, %d %s)" % (
            batch, prompt_len, n_new, len(jax.devices()), device_kind),
        "vs_baseline": 0.0,
        "ms_per_step": round(dt * 1000, 2),
    }), flush=True)


def bench_transformer():
    """BENCH_MODE=transformer: GPT flagship training MFU.

    Times the full causal-LM training step (fwd, softmax-CE over the
    padded vocab, bwd, SGD+momentum, bf16 compute / fp32 master) of a
    model-zoo GPT config.  This is the workload class TPUs are bought
    for: MFU is the headline, tokens/s the throughput.  FLOPs: matmul
    params contribute 6·N_matmul per token (fwd 2N + bwd 4N); attention
    adds 3.5 · 4·T²·H·D / 2 (causal) per layer per sequence.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    cfg_name, metric = _gpt_metric()
    n_layer, d_model, n_head = GPT_CONFIGS[cfg_name]

    platform = jax.devices()[0].platform
    _disarm_watchdog()
    device_kind = jax.devices()[0].device_kind
    on_cpu = platform == "cpu"
    seq = int(os.environ.get("BENCH_SEQ", "128" if on_cpu else "2048"))
    batch = int(os.environ.get("BENCH_BATCH", "2" if on_cpu else "8"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "2" if on_cpu else "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "1" if on_cpu else "3")))
    vocab = 50304 if not on_cpu else 512

    from mxnet_tpu.gluon.model_zoo import gpt
    from mxnet_tpu.gluon.block import functionalize

    # BENCH_REMAT=1: per-block rematerialisation (memory for FLOPs —
    # lets T or batch grow past HBM; MFU denominator stays the same)
    net = gpt.GPTLM(vocab, n_layer, d_model, n_head, max_len=seq,
                    remat=os.environ.get("BENCH_REMAT") == "1")
    net.initialize()
    toks0 = jnp.zeros((batch, seq), jnp.int32)
    fn, params = functionalize(net, toks0, train=True)
    mom = [jnp.zeros_like(p) for p in params]

    bench_dtype = os.environ.get(
        "BENCH_DTYPE", "float32" if on_cpu else "bfloat16")
    if bench_dtype not in ("bfloat16", "float32"):
        raise ValueError("BENCH_DTYPE must be bfloat16 or float32, got %r"
                         % bench_dtype)
    cdt = jnp.bfloat16 if bench_dtype == "bfloat16" else jnp.float32

    def loss_fn(ps, x, y):
        cps = [p.astype(cdt) for p in ps]
        (logits,), _ = fn(cps, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(ps, mom, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(ps, x, y)
        new_mom = [0.9 * m - 3e-4 * g.astype(jnp.float32)
                   for m, g in zip(mom, grads)]
        new_ps = [p + m for p, m in zip(ps, new_mom)]
        return new_ps, new_mom, loss

    key = jax.random.PRNGKey(0)
    x = jax.random.randint(key, (batch, seq), 0, vocab)
    y = jnp.roll(x, -1, axis=1)

    # analytic per-step training FLOPs: 6 FLOPs per matmul param per
    # token (embedding/position tables do no matmul work; the tied head
    # DOES matmul — count d·V once) + flash-attention score FLOPs
    n_matmul = n_layer * 12 * d_model * d_model + d_model * vocab
    attn = n_layer * 3.5 * 4 * seq * seq * d_model / 2
    step_flops = (6 * n_matmul * seq + attn) * batch

    for _ in range(warmup):
        params, mom, loss = train_step(params, mom, x, y)
    np.asarray(loss)  # completion barrier (PERF.md §1)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, mom, loss = train_step(params, mom, x, y)
    np.asarray(loss)
    dt = time.perf_counter() - t0

    tok_s = batch * seq * steps / dt
    result = {
        "metric": metric,
        "value": round(tok_s, 1),
        "unit": "tok/s (bs %d, T %d, vocab %d, %s, 1 %s device)" % (
            batch, seq, vocab, bench_dtype, platform),
        "vs_baseline": None,  # no reference counterpart (2017, pre-attention)
        "tflops": round(step_flops * steps / dt / 1e12, 1),
    }
    peak = PEAK_FLOPS.get(device_kind)
    if peak:
        result["mfu"] = round(step_flops * steps / dt / peak, 3)
    print(json.dumps(result))


def _synthetic_rec(n_images, edge, path):
    """Write an ImageNet-shaped synthetic .rec (JPEG-encoded random
    images) once; reruns reuse it.  Plays tools/im2rec.py's role without
    needing an image folder."""
    import numpy as np
    from mxnet_tpu import recordio

    if os.path.exists(path):
        return path
    from PIL import Image
    import io as pyio
    rng = np.random.RandomState(0)
    # write to a temp name, rename only on completion — an interrupted
    # generation must not leave a truncated .rec a later run benchmarks
    rec_tmp = path + ".partial"
    idx_final = path[:-4] + ".idx"
    idx_tmp = idx_final + ".partial"
    rec = recordio.MXIndexedRecordIO(idx_tmp, rec_tmp, "w")
    try:
        for i in range(n_images):
            img = rng.randint(0, 256, (edge, edge, 3), np.uint8)
            buf = pyio.BytesIO()
            Image.fromarray(img).save(buf, format="JPEG", quality=90)
            header = recordio.IRHeader(0, float(i % 1000), i, 0)
            rec.write_idx(i, recordio.pack(header, buf.getvalue()))
        rec.close()
        os.replace(rec_tmp, path)
        os.replace(idx_tmp, idx_final)
    except BaseException:
        rec.close()
        for f in (rec_tmp, idx_tmp):
            if os.path.exists(f):
                os.remove(f)
        raise
    return path


def bench_pipeline():
    """BENCH_MODE=pipeline: native input-pipeline throughput.

    Measures the C++ decode+augment pipeline (src/mxtpu/image_iter.cc)
    standalone — JPEG decode, 224 random crop, mirror, mean/std — the
    denominator for 'does IO sustain training' (PERF.md; the reference
    benchmarked the same via `--test-io 1`, example/image-classification/
    common/fit.py)."""
    import time as _time
    import numpy as np
    import jax
    import mxnet_tpu as mx

    jax.devices()  # backend init is the hang risk; prove it then disarm
    _disarm_watchdog()

    n_images = int(os.environ.get("BENCH_PIPE_IMAGES", "2000"))
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    threads = int(os.environ.get("BENCH_PIPE_THREADS", "8"))
    epochs = int(os.environ.get("BENCH_PIPE_EPOCHS", "3"))
    cache = os.environ.get("BENCH_PIPE_REC",
                           "/tmp/mxtpu_bench_synth_%d.rec" % n_images)
    _synthetic_rec(n_images, 256, cache)

    it = mx.io.ImageRecordIter(
        path_imgrec=cache, data_shape=(3, 224, 224), batch_size=batch,
        shuffle=True, rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.12, std_b=57.38,
        preprocess_threads=threads, prefetch_buffer=8)
    # warm epoch (thread pool spin-up, file cache)
    n = 0
    for b in it:
        n += batch
    t0 = _time.perf_counter()
    total = 0
    for _ in range(epochs):
        it.reset()
        for b in it:
            np.asarray(b.data[0]._data[0, 0, 0])  # pull one value
            total += batch
    dt = _time.perf_counter() - t0
    img_s = total / dt
    train_img_s = float(os.environ.get("BENCH_PIPE_TRAIN_IMG_S", "2235"))
    print(json.dumps({
        "metric": "input_pipeline_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s (jpeg decode + 224 crop/mirror/norm, %d threads, "
                "bs %d)" % (threads, batch),
        "vs_baseline": round(img_s / train_img_s, 3),
    }))


def bench_steptrace():
    """BENCH_MODE=steptrace: per-step XLA dispatch/compile counts of the
    fused Module.fit_step vs the split forward_backward+update pair on a
    small MLP fit loop — the regression tail for BENCH_*.json (the fused
    path must stay at exactly 1 dispatch/step, 0 steady-state compiles;
    see PERF.md, "Fused train step")."""
    import jax
    _perf_probe_path()
    import steptrace as _steptrace

    jax.devices()
    _disarm_watchdog()
    result = _steptrace.run()
    fused = result["fused"]
    unfused = result["unfused"]
    # the divergence guard rides INSIDE the fused program — folding it in
    # must not cost a dispatch.  Fail the bench loudly if it ever does.
    if fused["dispatches_per_step"] != 1.0:
        raise AssertionError(
            "guarded fused step dispatched %.3f programs/step (contract: "
            "exactly 1.0 — the divergence guard must stay inside the "
            "fused program)" % fused["dispatches_per_step"])
    fused_async = result["fused_async_ckpt"]
    if fused_async["dispatches_per_step"] != 1.0:
        raise AssertionError(
            "fused step with async checkpointing dispatched %.3f "
            "programs/step (contract: the snapshot+enqueue save path "
            "adds ZERO dispatches)" % fused_async["dispatches_per_step"])
    print(json.dumps({
        "metric": "fused_step_dispatches_per_step",
        "value": round(fused["dispatches_per_step"], 3),
        "unit": "dispatches/step (steady state; unfused=%s; %d params)"
                % (round(unfused["dispatches_per_step"], 3),
                   result["n_params"]),
        # 1.0 == the fused-path contract; anything above is a regression
        "vs_baseline": round(fused["dispatches_per_step"] / 1.0, 3),
        "steptrace": result,
    }))


def bench_spmd():
    """BENCH_MODE=spmd: the mesh-native ZeRO-1 fused step on an 8-device
    host mesh (tools/perf_probe/steptrace.run_spmd).  Hard contracts:

    - exactly 1.0 dispatch/step — the reduce-scatter, sharded update and
      all-gather all live INSIDE the one donated program;
    - 0 steady-state compiles;
    - opt-state bytes/device ~= 1/N of the total (replicated fallbacks
      for indivisible leaves get a small tolerance).
    """
    import jax
    _perf_probe_path()
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags and \
            jax.device_count() < 8:
        raise RuntimeError(
            "BENCH_MODE=spmd: fewer than 8 devices and no "
            "--xla_force_host_platform_device_count in XLA_FLAGS")
    import steptrace as _steptrace

    jax.devices()
    _disarm_watchdog()
    result = _steptrace.run_spmd()
    n = result["n_devices"]
    if result["dispatches_per_step"] != 1.0:
        raise AssertionError(
            "ZeRO-1 fused step dispatched %.3f programs/step (contract: "
            "exactly 1.0 — reduce-scatter/update/all-gather must stay "
            "inside the one donated program)"
            % result["dispatches_per_step"])
    if result["compile_count"] != 0:
        raise AssertionError(
            "ZeRO-1 fused step recompiled %d time(s) in steady state"
            % result["compile_count"])
    ratio = result["opt_state_total_bytes"] / \
        max(1, result["opt_state_bytes_per_device"])
    # the MLP's (4,) softmax bias state replicates (nothing divides 8);
    # everything else must be 1/N — so the aggregate factor sits just
    # under N but far above N/2
    if ratio < n / 2:
        raise AssertionError(
            "opt-state bytes/device %d vs total %d (factor %.2f): state "
            "is not sharded ~1/%d across the mesh"
            % (result["opt_state_bytes_per_device"],
               result["opt_state_total_bytes"], ratio, n))
    # compile-time attribution cross-check (OBSERVABILITY.md §8): the
    # compiled program's OWN per-device argument accounting
    # (xla.memory.argument_bytes) must agree ±20% with the bytes the
    # live arrays' shard shapes say each device holds — 1/N opt-state +
    # replicated params + 1/N batch.  An unsharded state tree would blow
    # this by ~2.4x (adam: two full state leaves vs two 1/N shards), so
    # the ZeRO economics are now asserted from the executable, not from
    # the placement model.
    arg_bytes = result["gauge_xla_memory_argument_bytes"]
    expected = result["expected_argument_bytes_per_device"]
    if not arg_bytes:
        raise AssertionError(
            "xla.memory.argument_bytes gauge not populated — the fused "
            "step's compile-time attribution is missing")
    if abs(arg_bytes - expected) > 0.2 * expected:
        raise AssertionError(
            "compiled per-device argument bytes %d vs %d expected from "
            "the sharded live arrays (>20%% apart): the program's "
            "memory accounting disagrees with the ZeRO-1 placement"
            % (arg_bytes, expected))
    if not result["gauge_collective_bytes_per_step"]:
        raise AssertionError(
            "sharding.collective_bytes_per_step gauge not populated "
            "from the compiled program's collective ops")
    print(json.dumps({
        "metric": "zero1_opt_state_shard_factor",
        "value": round(ratio, 3),
        "unit": "x smaller per device (n=%d, %d/%d leaves sharded, "
                "1.0 dispatch/step)"
                % (n, result["opt_state_leaves_sharded"],
                   result["opt_state_leaves"]),
        "vs_baseline": round(ratio / n, 3),
        "spmd": result,
    }))


def bench_telemetry():
    """BENCH_MODE=telemetry: always-on telemetry cost + phase breakdown.

    Runs the steptrace MLP fused fit loop with telemetry recording on
    (the production default) and with the hot path disabled
    (telemetry.set_enabled(False) — same switch as MXTPU_TELEMETRY_OFF)
    in many short alternating paired segments; the reported overhead is
    the median of the per-pair deltas, which cancels the slow drift that
    dwarfs a couple-of-µs effect on a ~0.3 ms CPU step.  Also reports
    the phase-time breakdown (fit_step.dispatch / fit_step.sync
    histograms).  Contract (OBSERVABILITY.md): overhead < 1% of the
    fused step, dispatch rate untouched at exactly 1.0/step."""
    import jax
    _perf_probe_path()
    import steptrace as _steptrace
    from mxnet_tpu import profiler, telemetry

    jax.devices()
    _disarm_watchdog()
    mod, train = _steptrace.build_module()
    batches = list(train)
    steps = max(1, int(os.environ.get("BENCH_STEPS", "200")))
    pairs = max(3, int(os.environ.get("BENCH_PAIRS", "12")))
    for _ in range(2):  # warm: trace + compile + allocator steady state
        for b in batches:
            mod.fit_step(b)

    def loop(n):
        t0 = time.perf_counter()
        for i in range(n):
            mod.fit_step(batches[i % len(batches)])
        return (time.perf_counter() - t0) / n

    deltas, offs = [], []
    try:
        for i in range(pairs):
            # alternate which side runs first so per-pair warmup/drift
            # doesn't systematically land on one side
            if i % 2:
                telemetry.set_enabled(True)
                on = loop(steps)
                telemetry.set_enabled(False)
                off = loop(steps)
            else:
                telemetry.set_enabled(False)
                off = loop(steps)
                telemetry.set_enabled(True)
                on = loop(steps)
            offs.append(off)
            deltas.append(on - off)
    finally:
        telemetry.set_enabled(True)

    telemetry.reset()
    profiler.reset_step_stats()
    measured = loop(steps)
    stats = profiler.step_stats()
    rep = telemetry.report()
    if stats["dispatch_count"] != steps:
        raise AssertionError(
            "telemetry run dispatched %d programs over %d steps "
            "(contract: exactly 1.0/step)" % (stats["dispatch_count"],
                                              steps))
    deltas.sort()
    offs.sort()
    delta = deltas[len(deltas) // 2]
    off = offs[len(offs) // 2]
    on = off + delta
    overhead_pct = delta / off * 100.0
    # the absolute per-step budget (OBSERVABILITY.md §8): the rank-
    # stamped hot path — one tuple append + the amortized batched
    # drain; job-scope identity/clock stamping is paid per report()
    # line, never per step — must stay within the ~2 µs always-on
    # budget.  Asserted on an ISOLATED microbench of the recording call
    # itself: the A/B fit-loop delta above is the honest end-to-end
    # number but carries several µs of scheduler noise on a shared box
    # (the seed measures ~10 µs of "overhead" by that method on a busy
    # machine), which would make an absolute gate on it meaningless.
    # The gate defaults to 2x the budget for interpreter jitter.
    telemetry.reset()
    iters = 20000
    base = time.perf_counter_ns()
    t0 = time.perf_counter()
    for i in range(iters):
        telemetry.note_train_step(base + i * 1000,
                                  base + i * 1000 + 500,
                                  base + i * 1000 + 800, False, None)
    hot_us = (time.perf_counter() - t0) / iters * 1e6
    telemetry.reset()
    budget_us = float(os.environ.get("MXTPU_TELEMETRY_BUDGET_US", "4"))
    if hot_us > budget_us:
        raise AssertionError(
            "telemetry hot path costs %.2f us/step isolated (budget "
            "%.1f us, ~2 us contract + headroom): the always-on "
            "per-step recording path regressed" % (hot_us, budget_us))
    phases = {
        name: {"count": p["count"],
               "mean_ms": round(1e3 * p["sum"] / p["count"], 4),
               "p50_ms": round(1e3 * p["p50"], 4),
               "p99_ms": round(1e3 * p["p99"], 4)}
        for name, p in rep["phases"].items() if p["count"]}
    print(json.dumps({
        "metric": "telemetry_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%% of fused CPU MLP step (median-paired on %.4f ms vs "
                "off %.4f ms, %d pairs x %d steps; budget 1%%)"
                % (on * 1e3, off * 1e3, pairs, steps),
        # vs the 1% always-on budget: <1.0 is within contract
        "vs_baseline": round(overhead_pct / 1.0, 3),
        "wall_ms_per_step": round(measured * 1e3, 4),
        "hot_path_us_per_step": round(hot_us, 3),
        "phases": phases,
        "flight": rep["flight"],
    }))


def bench_serve():
    """BENCH_MODE=serve: production inference serving (PERF.md §14).

    tools/perf_probe/serve_probe.py: an open-loop Poisson workload of
    mixed prompt/output lengths through the continuous-batching paged-KV
    ServingEngine vs the sequential per-request predictor baseline (one
    fixed-shape full forward per token — today's Predictor.forward
    discipline).  Hard contracts:

    - exactly 1.0 decode dispatch per token step (ALL resident
      sequences advance inside the one donated program);
    - 0 steady-state recompiles across request join/leave churn;
    - both servers emit bit-identical greedy tokens (asserted inside
      the probe);
    - continuous batching >= 2x the sequential baseline's tokens/s;
    - an AOT-warm replica reaches its first token with 0 foreground
      serving-program compiles (two subprocesses sharing a cache dir);
    - **degraded mode** (ISSUE 11): with one of two router replicas
      killed mid-probe (serve.replica.lost), every accepted request
      still completes with BIT-identical tokens to the unfaulted run,
      and the replacement replica spins up AOT-warm (0 foreground
      compiles) — with per-VERDICT deltas pinned (0 failed, exactly
      the killed replica's in-flight count retried);
    - **request-scope observability** (ISSUE 13): the per-decode-step
      tracing cost stays within MXTPU_SERVE_TRACE_BUDGET_US (default
      2 µs, isolated microbench), goodput == raw tokens on the
      unfaulted run, and serve_report run on the degraded drill's REAL
      artifact tree reconstructs every lifecycle (one terminal verdict
      each), links failovers across replicas by trace id, names the
      killed replica in the blame section, emits a single loadable
      merged chrome trace, and reconciles traced tokens with the
      serving.tokens counter bit-exactly;
    - **partition drill** (ISSUE 17): over a fleet sharing NO run dir
      (private per-worker tmp dirs, addr-pinned proxies), heartbeat-only
      loss raises suspicion with ZERO failovers and completes every
      request; a real partition confirms the typed `fence_expiry`
      reason, fails over, and fences the zombie's late completions —
      0 double-delivered, >= 1 fenced result, tokens bit-identical;
    - **telemetry plane** (ISSUE 18): the partition drill's router
      host assembles fleet telemetry ONLY via telemetry_pull and
      serve_report over that pull-only tree is green (lawful
      lifecycles, bit-exact token accounting, >= 1 default alert rule
      fired and rendered), fleet_top returns a complete live matrix,
      and a pull per engine step leaves 1.0 decode dispatch/step with
      0 recompiles, the steady-state pull itself under
      MXTPU_TELEMETRY_PULL_BUDGET (default 2000 us, isolated);
    - **speculative decoding** (ISSUE 16): on the acceptance-friendly
      workload spec-on reaches >= 1.5x spec-off tokens/s with > 1.3
      tokens per slot step, still exactly 1.0 decode dispatch/step and
      0 steady-state recompiles, greedy tokens bit-identical to
      spec-off, drafted == accepted + rejected, decode tokens ==
      slot_steps + accepted - discarded, and mixed greedy/sampled
      streams reproduce bit-exactly both on a re-run and across a
      router failover re-decode;
    - **quantized KV pages** (ISSUE 20): int8 pages + per-page-per-KV-
      head fp32 absmax scales vs bf16 pools — >= 1.8x residents in the
      same pool bytes, greedy token match-rate >= 0.99 vs the fp
      reference, kernel-vs-oracle dequant error <= 1e-5, and the hot
      path keeps 1.0 decode dispatch/step with 0 steady-state
      recompiles (quantize-on-scatter and dequant live INSIDE the one
      donated program);
    - **streamed delivery** (ISSUE 19): cursor-pull streaming delivers
      every accepted request's tokens EXACTLY ONCE — in-process
      (streamed TTFT p50 < 0.5x the unary completion p50, polling
      leaves 1.0 dispatch/step and 0 recompiles), across a real
      SIGKILL failover mid-stream (no gap, no duplicate, bit-identical
      to unfaulted; a blackholed poll reply recovered by an idempotent
      re-poll at the same cursor), under cancellation (typed
      `cancelled` verdict mid-decode AND queued, slot + KV pages back,
      survivors unperturbed), and under client vanish (the abandon
      sweep reclaims orphans with the typed `abandoned` verdict, page
      conservation green, the `orphan_reclaim` default alert fires).
    """
    import jax
    _perf_probe_path()
    import serve_probe

    jax.devices()
    _disarm_watchdog()
    result = serve_probe.run()
    cont = result["continuous"]
    trace_us = result["trace_overhead_us"]
    trace_budget = float(os.environ.get("MXTPU_SERVE_TRACE_BUDGET_US",
                                        "2"))
    if trace_us > trace_budget:
        raise AssertionError(
            "per-decode-step request tracing costs %.3f us isolated "
            "(budget %.1f us): the one-batched-event hot path "
            "regressed" % (trace_us, trace_budget))
    if not (cont["goodput_counter"] == cont["tokens_counter"]
            == cont["traced_tokens"] == cont["total_tokens"]):
        raise AssertionError(
            "unfaulted run accounting diverged: goodput=%d "
            "tokens_counter=%d traced=%d produced=%d (contract: all "
            "equal when nothing expires or fails)"
            % (cont["goodput_counter"], cont["tokens_counter"],
               cont["traced_tokens"], cont["total_tokens"]))
    if cont["decode_dispatches_per_step"] != 1.0:
        raise AssertionError(
            "serving decode dispatched %.3f programs/step (contract: "
            "exactly 1.0 — every resident sequence advances inside ONE "
            "donated program)" % cont["decode_dispatches_per_step"])
    if cont["steady_state_compiles"] != 0:
        raise AssertionError(
            "serving loop recompiled %d time(s) under request churn "
            "(contract: join/leave never changes a program shape)"
            % cont["steady_state_compiles"])
    spin = result["spinup"]
    if spin["warm_serve_compiles"] != 0:
        raise AssertionError(
            "AOT-warm replica spin-up compiled %d serving program(s) in "
            "the foreground (contract: 0 — first token comes off the "
            "deserialized executable)" % spin["warm_serve_compiles"])
    speedup = result["speedup_tokens_per_sec"]
    if speedup < 2.0:
        raise AssertionError(
            "continuous batching reached only %.2fx the sequential "
            "predictor baseline (contract: >= 2x tokens/s on the same "
            "mixed-length workload)" % speedup)
    pfx = result["prefix"]
    if pfx["hit_rate"] <= 0:
        raise AssertionError(
            "prefix-heavy workload produced a 0 hit-rate (contract: "
            "shared system prompts MUST hit the prefix cache)")
    if pfx["prefill_token_reduction"] < 0.30:
        raise AssertionError(
            "prefix caching cut prefill tokens by only %.1f%% on the "
            "system-prompt workload (%d -> %d; contract: >= 30%% fewer "
            "prefill tokens than cache-off on the same workload)"
            % (100 * pfx["prefill_token_reduction"],
               pfx["prefill_tokens_off"], pfx["prefill_tokens_on"]))
    if not pfx["tokens_match_cache_off"]:
        raise AssertionError(
            "cache-on tokens diverged from cache-off on the same "
            "workload (contract: prefix sharing changes capacity and "
            "prefill cost, NEVER tokens — greedy and sampled alike)")
    if pfx["decode_dispatches_per_step"] != 1.0:
        raise AssertionError(
            "with prefix cache + sampling enabled the decode loop "
            "dispatched %.3f programs/step (contract: exactly 1.0 — "
            "both multipliers ride the one-donated-program step)"
            % pfx["decode_dispatches_per_step"])
    if pfx["steady_state_compiles"] != 0:
        raise AssertionError(
            "prefix+sampling serving recompiled %d time(s) under churn "
            "(contract: per-request sampling params are program INPUTS, "
            "never a recompile)" % pfx["steady_state_compiles"])
    if pfx["sampling_requests"] < 1:
        raise AssertionError(
            "the prefix workload exercised no sampled requests — the "
            "sampling half of the contract is vacuous")
    gqa = result["gqa"]
    if gqa["kernel_max_err"] >= 1e-5:
        raise AssertionError(
            "GQA paged kernel diverged from the oracle at K_kv=%d "
            "(max err %.2e; contract: kernel-vs-oracle equivalence at "
            "mixed lengths)" % (gqa["kv_heads"], gqa["kernel_max_err"]))
    if gqa["pool_bytes_gqa"] > gqa["pool_bytes_mha"]:
        raise AssertionError(
            "GQA page pools used MORE bytes (%d) than the multi-head "
            "pools (%d) — the capacity comparison is unsound"
            % (gqa["pool_bytes_gqa"], gqa["pool_bytes_mha"]))
    if gqa["resident_multiplier"] < 1.5:
        raise AssertionError(
            "GQA at K_kv = H/2 fit only %.2fx residents in the same "
            "page-pool bytes (%d -> %d; contract: >= 1.5x)"
            % (gqa["resident_multiplier"], gqa["residents_mha"],
               gqa["residents_gqa"]))
    kvq = result["kvq"]
    if kvq["dequant_max_err"] > 1e-5:
        raise AssertionError(
            "quantized paged kernel diverged from the dequantizing "
            "oracle on the SAME int8 pools + scales (max err %.2e; "
            "contract: <= 1e-5 — in-kernel dequant is exact up to fp "
            "reassociation)" % kvq["dequant_max_err"])
    if kvq["pool_bytes_int8"] > kvq["pool_bytes_bf16"]:
        raise AssertionError(
            "int8 page pools used MORE bytes (%d) than the bf16 pools "
            "(%d) — the capacity comparison is unsound"
            % (kvq["pool_bytes_int8"], kvq["pool_bytes_bf16"]))
    if kvq["resident_multiplier"] < 1.8:
        raise AssertionError(
            "int8 KV pages fit only %.2fx residents in the same pool "
            "bytes as bf16 (%d -> %d; contract: >= 1.8x — payload "
            "halves, scale rows cost ~8*K_kv bytes/page)"
            % (kvq["resident_multiplier"], kvq["residents_bf16"],
               kvq["residents_int8"]))
    if kvq["token_match_rate"] < 0.99:
        raise AssertionError(
            "int8 greedy tokens matched the fp reference at only "
            "%.4f (contract: >= 0.99 — quantized greedy is pinned to "
            "itself, the match-rate gate pins its drift from fp)"
            % kvq["token_match_rate"])
    if kvq["decode_dispatches_per_step"] != 1.0:
        raise AssertionError(
            "with int8 KV pages the decode loop dispatched %.3f "
            "programs/step (contract: exactly 1.0 — quantize-on-"
            "scatter and in-kernel dequant ride the ONE donated "
            "program)" % kvq["decode_dispatches_per_step"])
    if kvq["steady_state_compiles"] != 0:
        raise AssertionError(
            "int8 serving recompiled %d time(s) under churn "
            "(contract: the page dtype is baked at engine build, "
            "never a steady-state shape change)"
            % kvq["steady_state_compiles"])
    spec = result["spec"]
    if spec["speedup_tokens_per_sec"] < 1.5:
        raise AssertionError(
            "speculative decoding reached only %.2fx spec-off tokens/s "
            "on the acceptance-friendly workload (contract: >= 1.5x — "
            "verified drafts must multiply tokens per dispatch)"
            % spec["speedup_tokens_per_sec"])
    if not spec["tokens_match_spec_off"]:
        raise AssertionError(
            "spec-on greedy tokens diverged from spec-off on the same "
            "workload (contract: acceptance emits the greedy chain "
            "itself — speculation changes throughput, NEVER tokens)")
    if spec["tokens_per_slot_step"] <= 1.3:
        raise AssertionError(
            "speculative decode committed only %.2f tokens per slot "
            "participation (contract: > 1.3 — a non-speculative slot "
            "step is exactly 1.0)" % spec["tokens_per_slot_step"])
    if spec["decode_dispatches_per_step"] != 1.0:
        raise AssertionError(
            "with speculation enabled the decode loop dispatched %.3f "
            "programs/step (contract: exactly 1.0 — draft + verify + "
            "accept ride the ONE donated program)"
            % spec["decode_dispatches_per_step"])
    if spec["steady_state_compiles"] != 0:
        raise AssertionError(
            "speculative serving recompiled %d time(s) under churn "
            "(contract: draft length is a MASK, never a shape)"
            % spec["steady_state_compiles"])
    if not spec["counter_identity_draft"] or \
            not spec["counter_identity_tokens"]:
        raise AssertionError(
            "spec counters do not reconcile (drafted=%d accepted=%d "
            "rejected=%d; contract: drafted == accepted + rejected AND "
            "decode tokens == slot_steps + accepted - discarded)"
            % (spec["draft_tokens"], spec["accepted"],
               spec["rejected"]))
    if spec["spec_off_drafted"] != 0:
        raise AssertionError(
            "the spec-off arm drafted %d token(s) (contract: spec_k=0 "
            "means the drafter never runs)" % spec["spec_off_drafted"])
    if not spec["sampled_repro_match"]:
        raise AssertionError(
            "a mixed greedy/sampled spec-on run did not repeat "
            "bit-identically (contract: per-request functional PRNG — "
            "same seed, same stream)")
    if spec["failover_completed"] != spec["requests"] or \
            spec["failover_failovers"] < 1 or \
            not spec["failover_tokens_match"]:
        raise AssertionError(
            "spec-on router failover broke determinism (%d/%d "
            "completed, %d failover(s), tokens_match=%s; contract: "
            "sampled AND greedy streams survive the replacement "
            "replica's re-decode bit-exactly)"
            % (spec["failover_completed"], spec["requests"],
               spec["failover_failovers"],
               spec["failover_tokens_match"]))
    deg = result["degraded"]
    if deg["dropped"] != 0:
        raise AssertionError(
            "degraded mode dropped %d accepted request(s) after a "
            "replica kill (contract: the router completes every "
            "accepted request exactly once)" % deg["dropped"])
    if not deg["tokens_match_unfaulted"]:
        raise AssertionError(
            "degraded-mode tokens diverged from the unfaulted run "
            "(contract: failover re-decode is bit-identical greedy)")
    if deg["failovers"] < 1:
        raise AssertionError(
            "degraded mode observed no failover — the replica kill "
            "never landed; the contract was not exercised")
    if deg["replacement_foreground_compiles"] != 0:
        raise AssertionError(
            "replacement replica compiled %d serving program(s) in the "
            "foreground (contract: AOT/memo-warm spin-up)"
            % deg["replacement_foreground_compiles"])
    if deg["failed"] != 0:
        raise AssertionError(
            "degraded mode left %d request(s) with verdict `failed` "
            "(contract: 0 — a replica kill retries, never fails)"
            % deg["failed"])
    if deg["retried"] != deg["expected_retried"]:
        raise AssertionError(
            "degraded mode retried %s request(s) but the killed "
            "replica held exactly %s in flight (contract: the retry "
            "set IS the victim's in-flight set — verdict accounting, "
            "not just totals)" % (deg["retried"],
                                  deg["expected_retried"]))
    rep = deg["report"]
    if not rep["lifecycle_ok"]:
        raise AssertionError(
            "serve_report on the degraded artifact tree found "
            "lifecycle violations %s + %d open trace(s) (contract: "
            "every accepted request reconstructs with exactly one "
            "terminal verdict)" % (rep["violations"],
                                   rep["open_traces"]))
    if rep["arcs"] < 1 or rep["linked_arcs"] != rep["arcs"]:
        raise AssertionError(
            "serve_report linked %d of %d failover arc(s) across "
            "replicas by trace id (contract: every failed-over "
            "request links victim -> survivor)"
            % (rep["linked_arcs"], rep["arcs"]))
    if not rep["killed_replica_blamed"]:
        raise AssertionError(
            "serve_report's blame section did not name the killed "
            "replica %r" % rep["killed_replica"])
    if rep["trace_file_events"] < 1:
        raise AssertionError(
            "the merged serve chrome trace did not round-trip as one "
            "loadable JSON document")
    if not rep["token_accounting_exact"]:
        raise AssertionError(
            "traced token events (%s) did not reconcile bit-exactly "
            "with the serving.tokens counter (%s) on the degraded "
            "drill" % (rep["traced_tokens"], rep["tokens_counter"]))
    fleet = result["fleet"]
    if fleet["dropped"] != 0:
        raise AssertionError(
            "fleet drill dropped %d accepted request(s) after the "
            "replica-process SIGKILL (contract: the router completes "
            "every accepted request exactly once across real process "
            "death)" % fleet["dropped"])
    if not fleet["tokens_match_unfaulted"]:
        raise AssertionError(
            "fleet-drill tokens diverged from the unfaulted run "
            "(contract: the out-of-process failover re-decode is "
            "bit-identical greedy)")
    if fleet["failovers"] < 1:
        raise AssertionError(
            "fleet drill observed no failover — the "
            "serve.replica.sigkill never landed; the contract was "
            "not exercised")
    if fleet["replacement_spawns"] < 1:
        raise AssertionError(
            "the fleet drill never spawned a replacement process — "
            "the AOT-warm-replacement contract was not exercised "
            "(Router tolerates spawn failures on survivors; the DRILL "
            "must not)")
    if fleet["replacement_foreground_compiles"] != 0:
        raise AssertionError(
            "the replacement replica PROCESS compiled %d serving "
            "program(s) in the foreground (contract: 0 — it "
            "deserializes the fleet's shared AOT cache)"
            % fleet["replacement_foreground_compiles"])
    br = fleet["breaker"]
    if br["trips"] < 1 or not br["recovered"] or \
            br["final_state"] != "closed":
        raise AssertionError(
            "circuit breaker did not trip and recover under rpc.drop "
            "(trips=%s, final=%s; contract: consecutive timeouts trip "
            "it open, the half-open probe closes it once the replica "
            "heals)" % (br["trips"], br["final_state"]))
    if br["completed"] != br["requests"]:
        raise AssertionError(
            "breaker drill completed %d of %d requests (contract: a "
            "tripped breaker re-routes intake, it never strands a "
            "request)" % (br["completed"], br["requests"]))
    if br["served_by_b_after_recovery"] < 1:
        raise AssertionError(
            "no post-recovery request was served by the healed "
            "replica (contract: a closed breaker restores placement)")
    part = result["partition"]
    pha = part["phase_a"]
    if pha["suspicions"] < 1:
        raise AssertionError(
            "heartbeat-only loss raised no suspicion (contract: a cut "
            "control plane is OBSERVED — rpc.suspicions counts it)")
    if pha["failovers"] != 0 or pha["confirm_reason"] is not None:
        raise AssertionError(
            "heartbeat-only loss caused %d failover(s) (reason=%s; "
            "contract: suspicion NEVER fails over a replica whose "
            "data plane still makes progress)"
            % (pha["failovers"], pha["confirm_reason"]))
    if pha["completed"] != pha["requests"]:
        raise AssertionError(
            "heartbeat-only loss completed %d of %d requests "
            "(contract: a suspected-but-working replica serves on)"
            % (pha["completed"], pha["requests"]))
    if not pha["suspect_cleared"]:
        raise AssertionError(
            "suspicion did not clear after the control plane healed "
            "(contract: suspicion is reversible, confirmation is not)")
    if part["failovers"] <= pha["failovers"] or \
            part["confirm_reason"] != "fence_expiry" or \
            part["confirmations_fence_expiry"] < 1:
        raise AssertionError(
            "the partition drill never confirmed fence_expiry "
            "(failovers=%d, reason=%r; contract: heartbeat AND "
            "progress silence past the lease is the typed partition "
            "verdict)" % (part["failovers"], part["confirm_reason"]))
    if part["dropped"] != 0 or part["double_delivered"] != 0:
        raise AssertionError(
            "partition drill dropped %d / double-delivered %d "
            "request(s) (contract: exactly-once — one terminal "
            "journal line per rid, fenced zombies rejected)"
            % (part["dropped"], part["double_delivered"]))
    if part["fenced_results"] < 1 or \
            part["fenced_journal_lines"] < 1:
        raise AssertionError(
            "the zombie's late completions were never fenced "
            "(fenced_results=%d, journal lines=%d; contract: the "
            "healed partition's write-backs are observed and "
            "REJECTED, never silently unread)"
            % (part["fenced_results"], part["fenced_journal_lines"]))
    if not part["tokens_match_unfaulted"]:
        raise AssertionError(
            "partition-drill tokens diverged from the unfaulted run "
            "(contract: the fenced failover re-decode is bit-identical "
            "greedy)")
    coll = result["collector"]
    pull_budget = float(os.environ.get("MXTPU_TELEMETRY_PULL_BUDGET",
                                       "2000"))
    if coll["decode_dispatches_per_step"] != 1.0 or \
            coll["steady_state_compiles"] != 0:
        raise AssertionError(
            "a telemetry pull per engine step broke the hot path "
            "(%.3f dispatch/step, %d recompile(s); contract: the "
            "collector NEVER forces a dispatch or a recompile)"
            % (coll["decode_dispatches_per_step"],
               coll["steady_state_compiles"]))
    if coll["pull_us"] > pull_budget:
        raise AssertionError(
            "a steady-state telemetry pull costs %.1f us isolated "
            "(MXTPU_TELEMETRY_PULL_BUDGET %.0f us): the pull_snapshot "
            "path regressed" % (coll["pull_us"], pull_budget))
    tel = part["telemetry"]
    if not (tel["lifecycle_ok"] and tel["accounting_exact"]):
        raise AssertionError(
            "serve_report on the PULL-ONLY partition tree was not "
            "green (lifecycle_ok=%s accounting_exact=%s tokens=%s "
            "traced=%s; contract: the router host's telemetry_pull "
            "collector assembles the complete fleet record — no "
            "shared-filesystem reads)"
            % (tel["lifecycle_ok"], tel["accounting_exact"],
               tel["tokens"], tel["traced_tokens"]))
    if tel["alerts_fired"] < 1 or not tel["report_renders"]:
        raise AssertionError(
            "no default alert rule fired/rendered during the "
            "partition drill (fired=%d rules=%s renders=%s; contract: "
            "an open breaker or a fence confirmation trips the "
            "default rules and the alerts lane shows it)"
            % (tel["alerts_fired"], tel["alert_rules"],
               tel["report_renders"]))
    if tel["fleet_top"]["rows"] != 2 or \
            not tel["fleet_top"]["complete"]:
        raise AssertionError(
            "fleet_top's live matrix was incomplete on the drill "
            "fleet (%s; contract: one complete row per live worker "
            "via status + telemetry_pull alone)" % (tel["fleet_top"],))
    stream = result["stream"]
    sm = stream["streamed"]
    if not sm["exactly_once"]:
        raise AssertionError(
            "in-process streaming broke exactly-once assembly "
            "(contract: the cursor-pull chunks concatenate to the "
            "engine's token list — no gap, no duplicate)")
    if sm["decode_dispatches_per_step"] != 1.0 or \
            sm["steady_state_compiles"] != 0:
        raise AssertionError(
            "polling the stream broke the hot path (%.3f "
            "dispatch/step, %d recompile(s); contract: poll reads a "
            "host-side buffer — it NEVER touches the donated program)"
            % (sm["decode_dispatches_per_step"],
               sm["steady_state_compiles"]))
    if sm["ttft_vs_unary_ratio"] >= 0.5:
        raise AssertionError(
            "streamed TTFT p50 (%.1fms) is %.2fx the unary completion "
            "p50 (%.1fms) on the mixed-length workload (contract: "
            "< 0.5x — the first chunk must beat the full reply)"
            % (sm["streamed_ttft_p50_ms"], sm["ttft_vs_unary_ratio"],
               sm["unary_completion_p50_ms"]))
    can = stream["cancel"]
    if can["mid_decode_verdict"] != "cancelled" or \
            can["queued_verdict"] != "cancelled" or \
            not can["idempotent"]:
        raise AssertionError(
            "cancel did not land the typed terminal verdict "
            "(mid_decode=%r queued=%r idempotent=%s; contract: "
            "`cancelled` between decode steps, for queued requests, "
            "and a repeat cancel is a no-op)"
            % (can["mid_decode_verdict"], can["queued_verdict"],
               can["idempotent"]))
    if not (can["survivors_completed"] and can["survivor_tokens_match"]
            and can["pages_restored"] and can["conservation_ok"]):
        raise AssertionError(
            "cancellation perturbed the batch (survivors_completed=%s "
            "tokens_match=%s pages_restored=%s conservation=%s; "
            "contract: a cancel frees slot + KV pages and the "
            "survivors' greedy streams are untouched)"
            % (can["survivors_completed"],
               can["survivor_tokens_match"], can["pages_restored"],
               can["conservation_ok"]))
    van = stream["vanish"]
    if van["orphans"] < 1 or not van["abandoned_verdicts"] or \
            van["abandoned_counter"] < van["orphans"]:
        raise AssertionError(
            "the serve.client.vanish drill reclaimed no orphan "
            "(orphans=%s verdicts_ok=%s counter=%s; contract: a "
            "stream unpolled past MXTPU_SERVE_ABANDON_S lands the "
            "typed `abandoned` verdict + counter)"
            % (van["orphans"], van["abandoned_verdicts"],
               van["abandoned_counter"]))
    if not (van["pages_restored"] and van["conservation_ok"]
            and van["survivors_completed"]
            and van["survivor_streams_exact"]):
        raise AssertionError(
            "orphan reclamation leaked (pages_restored=%s "
            "conservation=%s survivors_completed=%s survivors_exact=%s"
            "; contract: reclaim returns every page to the free pool "
            "with the conservation audit green and live pollers "
            "unperturbed)"
            % (van["pages_restored"], van["conservation_ok"],
               van["survivors_completed"],
               van["survivor_streams_exact"]))
    if not van["alert_fired"]:
        raise AssertionError(
            "the orphan_reclaim default alert did not fire on the "
            "vanish drill (contract: abandoned-counter movement trips "
            "the default rule)")
    sf = stream["fleet"]
    if sf["dropped"] != 0 or not sf["exactly_once"]:
        raise AssertionError(
            "the kill-mid-stream fleet drill broke exactly-once "
            "delivery (dropped=%d exactly_once=%s; contract: every "
            "accepted request's tokens arrive exactly once across a "
            "real SIGKILL failover — no gap, no duplicate)"
            % (sf["dropped"], sf["exactly_once"]))
    if not sf["tokens_match_unfaulted"]:
        raise AssertionError(
            "streamed fleet tokens diverged from the unfaulted "
            "reference (contract: the survivor's re-decode is "
            "bit-identical, so the cursor stays valid across the "
            "kill)")
    if sf["failovers"] < 1 or not sf["killed_mid_stream"] or \
            sf["streams_resumed_across_kill"] < 1:
        raise AssertionError(
            "the SIGKILL never landed mid-stream (failovers=%d "
            "mid_stream=%s resumed=%d; contract: >= 1 stream with a "
            "non-zero cursor at kill time resumes on the replacement "
            "with no client-visible gap)"
            % (sf["failovers"], sf["killed_mid_stream"],
               sf["streams_resumed_across_kill"]))
    if sf["drop_blackholed_replies"] < 1 or \
            not sf["drop_repoll_contiguous"]:
        raise AssertionError(
            "the serve.stream.drop site never bit, or the re-poll "
            "tore the stream (blackholed=%d contiguous=%s; contract: "
            "a dropped poll reply is recovered by an idempotent "
            "re-poll at the SAME cursor)"
            % (sf["drop_blackholed_replies"],
               sf["drop_repoll_contiguous"]))
    if sf["replacement_spawns"] < 1:
        raise AssertionError(
            "the streamed fleet drill never spawned a replacement — "
            "the resume-across-failover contract was not exercised")
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": cont["tokens_per_sec"],
        "unit": "tok/s (%d reqs Poisson, %d slots busy %.1f avg, ttft "
                "p50 %.1fms p99 %.1fms, tpot p50 %.2fms; sequential "
                "baseline %.1f tok/s; warm spin-up %.2fs/%d compiles)"
                % (cont["requests"], cont["num_slots"],
                   cont["mean_batch_occupancy"],
                   cont["ttft_p50_ms"], cont["ttft_p99_ms"],
                   cont["tpot_p50_ms"],
                   result["sequential"]["tokens_per_sec"],
                   spin["warm_ttfb_s"], spin["warm_serve_compiles"]),
        # the >=2x continuous-batching contract; >=1.0 is within it
        "vs_baseline": round(speedup / 2.0, 3),
        "speedup": speedup,
        "trace_overhead_us": trace_us,
        "collector_pull_us": coll["pull_us"],
        "partition_alerts_fired": tel["alerts_fired"],
        "prefix_prefill_token_reduction":
            pfx["prefill_token_reduction"],
        "prefix_hit_rate": pfx["hit_rate"],
        "gqa_resident_multiplier": gqa["resident_multiplier"],
        "kvq_resident_multiplier": kvq["resident_multiplier"],
        "kvq_token_match_rate": kvq["token_match_rate"],
        "kvq_dequant_max_err": kvq["dequant_max_err"],
        "spec_speedup": spec["speedup_tokens_per_sec"],
        "spec_tokens_per_slot_step": spec["tokens_per_slot_step"],
        "spec_acceptance_rate": spec["acceptance_rate"],
        "streamed_ttft_p50_ms": sm["streamed_ttft_p50_ms"],
        "streamed_ttft_vs_unary": sm["ttft_vs_unary_ratio"],
        "stream_orphans_reclaimed": van["orphans"],
        "stream_kill_resumed": sf["streams_resumed_across_kill"],
        "serve": result,
    }))


def bench_graph():
    """BENCH_MODE=graph: the graph rewrite pipeline's contract
    (PERF.md §15, tools/perf_probe/graph_probe.py).  Hard contracts:

    - >= 15% fewer lowered-HLO instructions with the pipeline on vs off
      on BOTH bench graphs (the ResNet conv→bn→relu tower and the
      post-LN GPT stack) — the instruction-count contract is measured
      on the pre-optimization module the graph stage hands XLA;
    - pipeline-on outputs equivalent to pipeline-off (rtol 1e-6);
    - steptrace invariants with the pipeline enabled: exactly 1.0
      dispatch/step, 0 steady-state recompiles on a fused fit loop over
      a fusable (conv→bn→relu) net.

    The measured forward step-time ratio is reported alongside (the
    headline unit string carries it)."""
    import jax
    _perf_probe_path()
    import graph_probe

    jax.devices()
    _disarm_watchdog()
    result = graph_probe.run()
    contract = result["hlo_contract"]
    for name in ("resnet", "gpt"):
        side = result[name]
        if side["lowered_reduction"] < contract:
            raise AssertionError(
                "%s bench graph: pipeline cut lowered-HLO instructions "
                "by only %.1f%% (%d -> %d; contract >= %.0f%%)"
                % (name, side["lowered_reduction"] * 100,
                   side["lowered_instructions_off"],
                   side["lowered_instructions_on"], contract * 100))
        if side["max_rel_err"] > 1e-6:
            raise AssertionError(
                "%s bench graph: pipeline-on output diverged from "
                "pipeline-off (max rel err %.3g > 1e-6)"
                % (name, side["max_rel_err"]))
    st = result["steptrace"]
    if st["dispatches_per_step"] != 1.0:
        raise AssertionError(
            "fused fit loop with the pipeline enabled dispatched %.3f "
            "programs/step (contract: exactly 1.0)"
            % st["dispatches_per_step"])
    if st["compile_count"] != 0:
        raise AssertionError(
            "fused fit loop with the pipeline enabled recompiled %d "
            "time(s) in steady state (contract: 0)" % st["compile_count"])
    worst = min(result["resnet"]["lowered_reduction"],
                result["gpt"]["lowered_reduction"])
    print(json.dumps({
        "metric": "graph_pipeline_hlo_reduction",
        "value": round(worst * 100, 2),
        "unit": "%% fewer lowered-HLO instructions (worst graph; resnet "
                "%.1f%% %d->%d fwd x%.2f, gpt %.1f%% %d->%d fwd "
                "x%.2f; 1.0 dispatch/step, 0 recompiles)" % (
                    result["resnet"]["lowered_reduction"] * 100,
                    result["resnet"]["lowered_instructions_off"],
                    result["resnet"]["lowered_instructions_on"],
                    result["resnet"]["fwd_speedup"],
                    result["gpt"]["lowered_reduction"] * 100,
                    result["gpt"]["lowered_instructions_off"],
                    result["gpt"]["lowered_instructions_on"],
                    result["gpt"]["fwd_speedup"]),
        "vs_baseline": round(worst / contract, 3),
        "graph": result,
    }))


def bench_restart():
    """BENCH_MODE=restart: fault tolerance off the hot path.

    Two numbers (tools/perf_probe/restart_probe.py, CPU micro-bench):
    per-checkpoint step stall sync vs async (p50/p99 of the wall time
    save_checkpoint blocks the step loop; contract ≥5× lower async) and
    restart time-to-first-step cold vs warm (fresh subprocesses sharing
    one AOT executable cache, the launch.py restart setup; contract ≥2×
    faster warm).  Headline value is the p50 stall ratio;
    vs_baseline is that ratio against the 5× contract."""
    import jax
    _perf_probe_path()
    import restart_probe

    jax.devices()
    _disarm_watchdog()
    result = restart_probe.run()
    stall = result["stall"]
    ttfs = result["ttfs"]
    print(json.dumps({
        "metric": "ckpt_stall_sync_over_async",
        "value": stall["ratio_p50"],
        "unit": "x lower per-ckpt step stall (sync p50 %.2fms p99 %.2fms"
                " -> async p50 %.2fms p99 %.2fms; warm restart"
                " time-to-first-step %.2fx: cold %.2fs -> warm %.2fs,"
                " warm compiles %d)" % (
                    stall["sync"]["p50_ms"], stall["sync"]["p99_ms"],
                    stall["async"]["p50_ms"], stall["async"]["p99_ms"],
                    ttfs["speedup"], ttfs["cold_s"], ttfs["warm_s"],
                    ttfs["warm_fit_step_compiles"]),
        # the ≥5x async-stall contract; ≥1.0 is within it
        "vs_baseline": round(stall["ratio_p50"] / 5.0, 3),
        "warm_ttfs_speedup": ttfs["speedup"],
        "restart": result,
    }))


def bench_stream():
    """BENCH_MODE=stream: streaming ingest vs the in-memory DataLoader
    (tools/perf_probe/stream_probe.py).  Hard contracts (DATA.md):

    - steady-state fused-step time from disk shards within
      MXTPU_STREAM_BENCH_MAX_RATIO (default 1.10x) of the in-memory
      DataLoader on the same data — decode hidden by the worker pool;
    - io.queue_wait p99 bounded below one in-memory step;
    - exactly 1.0 dispatch/step, 0 steady-state recompiles.
    """
    import jax
    _perf_probe_path()
    import stream_probe as _stream_probe

    jax.devices()
    _disarm_watchdog()
    result = _stream_probe.run()
    _stream_probe.check(result)
    print(json.dumps({
        "metric": "stream_vs_inmem_step_ratio",
        "value": result["ratio_stream_vs_mem"],
        "unit": "x in-memory step (median of %d pairs; queue-wait p99 "
                "%.3f ms; 1.0 dispatch/step)"
                % (len(result["ratio_pairs"]),
                   result["io_queue_wait_p99_ms"]),
        # 1.0 == parity with in-memory; the contract ceiling is 1.10
        "vs_baseline": round(result["ratio_stream_vs_mem"], 3),
        "stream": result,
    }))


def main():
    mode = os.environ.get("BENCH_MODE")
    network = os.environ.get("BENCH_NETWORK", "resnet50_v1")
    if network not in NETWORKS:
        raise ValueError("BENCH_NETWORK must be one of %s, got %r"
                         % (sorted(NETWORKS), network))
    metric, unit = {
        "attention": ("flash_attention_train_tflops", "TFLOP/s"),
        "pipeline": ("input_pipeline_images_per_sec", "img/s"),
        "steptrace": ("fused_step_dispatches_per_step", "dispatches/step"),
        "spmd": ("zero1_opt_state_shard_factor", "x"),
        "telemetry": ("telemetry_overhead_pct", "%"),
        "restart": ("ckpt_stall_sync_over_async", "x"),
        "serve": ("serving_tokens_per_sec", "tok/s"),
        "graph": ("graph_pipeline_hlo_reduction", "%"),
        "stream": ("stream_vs_inmem_step_ratio", "x"),
        "transformer": (_gpt_metric()[1] if mode == "transformer"
                        else "", "tok/s"),
        "generate": (_gpt_metric("generate")[1] if mode == "generate"
                     else "", "tok/s"),
    }.get(mode, (_network_metric(network), "img/s"))
    _install_init_watchdog(metric, unit)
    try:
        _run_mode(mode, network)
        _tier1_margin_gate()
    except (SystemExit, KeyboardInterrupt):
        # the driver-row guarantee below is for genuine failures only;
        # Ctrl-C keeps its conventional interrupt exit (ADVICE r5)
        raise
    except BaseException as e:  # noqa: BLE001 — the driver needs a row
        # a mid-run failure (tunnel RPC death, compile error) must still
        # produce the one parseable JSON line the driver records; the
        # round-5 headline run died with a raw traceback and the round's
        # BENCH artifact was garbage (PERF.md §7b)
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": "%s (measurement unavailable)" % unit,
            "vs_baseline": 0.0,
            "error": "benchmark crashed mid-run: %s: %s"
                     % (type(e).__name__, str(e)[:300]),
        }), flush=True)
        sys.exit(4)


def _run_mode(mode, network):
    if mode == "attention":
        bench_attention()
        return
    if mode == "pipeline":
        bench_pipeline()
        return
    if mode == "transformer":
        bench_transformer()
        return
    if mode == "generate":
        bench_generate()
        return
    if mode == "steptrace":
        bench_steptrace()
        return
    if mode == "spmd":
        bench_spmd()
        return
    if mode == "telemetry":
        bench_telemetry()
        return
    if mode == "restart":
        bench_restart()
        return
    if mode == "serve":
        bench_serve()
        return
    if mode == "graph":
        bench_graph()
        return
    if mode == "stream":
        bench_stream()
        return
    # bs 128 is the measured single-chip sweet spot on v5e (PERF.md:
    # 2379 img/s vs 2263 at bs 256, 2114 at bs 512)
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "3")))
    default_image = "299" if network == "inception_v3" else "224"
    image = int(os.environ.get("BENCH_IMAGE", default_image))

    import numpy as np
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    _disarm_watchdog()
    device_kind = jax.devices()[0].device_kind
    if platform == "cpu" and "BENCH_BATCH" not in os.environ:
        batch, steps = 16, 4  # keep the CPU smoke test fast

    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.block import functionalize

    net = getattr(vision, network)(classes=1000)
    net.initialize()
    x0 = jnp.zeros((batch, 3, image, image), jnp.float32)
    fn, params = functionalize(net, x0, train=True)
    n_aux = fn.num_aux
    n_diff = len(params) - n_aux
    diff_params = params[:n_diff]
    aux_params = params[n_diff:]
    mom = [jnp.zeros_like(p) for p in diff_params]

    # mixed precision: bf16 activations/weights on the MXU, fp32 master
    # weights + fp32 update (the reference's mp_sgd fp16 recipe,
    # src/operator/optimizer_op.cc; BENCH_DTYPE=float32 opts out)
    bench_dtype = os.environ.get(
        "BENCH_DTYPE", "bfloat16" if platform != "cpu" else "float32")
    if bench_dtype not in ("bfloat16", "float32"):
        raise ValueError("BENCH_DTYPE must be bfloat16 or float32, got %r"
                         % bench_dtype)
    cdt = jnp.bfloat16 if bench_dtype == "bfloat16" else jnp.float32

    def loss_fn(diff, aux, x, y, rng):
        cdiff = [p.astype(cdt) for p in diff]
        (logits,), new_aux = fn(cdiff + list(aux), x.astype(cdt), rng=rng)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
        return loss, new_aux

    # donate params/aux/momentum: the step updates them in place in HBM
    # (PlanMemory's inplace discipline, done by XLA buffer donation)
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(diff, aux, mom, x, y, rng):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(diff, aux, x, y, rng)
        new_mom = [0.9 * m - 0.05 * g.astype(jnp.float32)
                   for m, g in zip(mom, grads)]
        new_diff = [p + m for p, m in zip(diff, new_mom)]
        return new_diff, list(new_aux), new_mom, loss

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, 3, image, image), jnp.float32)
    y = jax.random.randint(key, (batch,), 0, 1000)

    # Per-step training FLOPs for the MFU report.  Analytic by default:
    # ResNet-50 forward at 224² is 4.089 GMACs (stem+4 stages+fc, standard
    # count) → 8.18 GFLOPs; training ≈ 3× forward (one fwd + two bwd
    # matmul passes) = 24.5 GFLOPs/img, scaled by the spatial area.
    # BENCH_COST_ANALYSIS=1 uses XLA's own count instead (an AOT
    # lower().compile() — it bypasses the jit compile cache and is
    # extremely slow through the axon tunnel, so it is opt-in; XLA counts
    # ~22.5 GFLOPs/img for this program, 8% under the analytic figure).
    if os.environ.get("BENCH_COST_ANALYSIS") == "1":
        ca = train_step.lower(diff_params, aux_params, mom, x, y,
                              key).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        step_flops = float(ca.get("flops", 0.0)) or None
    else:
        base_image = 299.0 if network == "inception_v3" else 224.0
        gmacs = NETWORKS[network][1]
        step_flops = 3 * 2 * gmacs * 1e9 * batch * (image / base_image) ** 2

    for i in range(warmup):
        diff_params, aux_params, mom, loss = train_step(
            diff_params, aux_params, mom, x, y, jax.random.fold_in(key, i))
    np.asarray(loss)  # completion barrier (see module docstring)

    # BENCH_PROFILE=<dir>: capture an xplane/trace of the timed loop for
    # tensorboard / xprof analysis (the profiler story for perf work)
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    try:
        t0 = time.perf_counter()
        for i in range(steps):
            diff_params, aux_params, mom, loss = train_step(
                diff_params, aux_params, mom, x, y,
                jax.random.fold_in(key, i))
        np.asarray(loss)  # forces the whole donated-param chain
        dt = time.perf_counter() - t0
    finally:
        if profile_dir:
            jax.profiler.stop_trace()  # flush even when a step dies

    img_s = batch * steps / dt
    baseline = NETWORKS[network][0]
    result = {
        "metric": _network_metric(network),
        "value": round(img_s, 2),
        "unit": "img/s (bs %d, %dx%d, %s, 1 %s device)" % (
            batch, image, image, bench_dtype, platform),
        # null (not 0.0 — the watchdog's failure sentinel) when the
        # reference README published no number for this network
        "vs_baseline": round(img_s / baseline, 3) if baseline else None,
    }
    if step_flops:
        tflops = step_flops * steps / dt / 1e12
        result["tflops"] = round(tflops, 1)
        peak = PEAK_FLOPS.get(device_kind)
        if peak:
            result["mfu"] = round(step_flops * steps / dt / peak, 3)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
