"""Headline benchmark: ResNet-50 training throughput (img/s) on one chip.

Baseline (BASELINE.md): MXNet v0.11 ResNet-50 ImageNet at batch 32 on one
K80 = 109 img/s (/root/reference/example/image-classification/README.md:147-157).
Here: the same model family (gluon model_zoo ResNet-50 v1) compiled to one
XLA program — forward, softmax-CE loss, backward, SGD+momentum update —
per step, images 224x224x3.

Timing methodology (round 3): the axon TPU tunnel's `block_until_ready`
returns before device completion, so a device→host fetch of the final
loss scalar is the only reliable completion barrier — every step's loss
depends on the previous step's (donated) params, so fetching the last
loss forces the whole chain.  Rounds 1-2 numbers (~2180 img/s at bs 256)
were dispatch-bound under-measurements; see PERF.md for the full analysis.

MFU is computed from the compiled step's XLA cost analysis against the
chip's nominal bf16 peak.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import functools
import json
import os
import sys
import time

BASELINE_IMG_S = 109.0  # 1x K80, bs 32, reference README

# nominal dense bf16 peak FLOP/s by device kind (for the MFU report)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def bench_attention():
    """BENCH_MODE=attention: Pallas flash-attention step vs chip peak.

    Times fwd+bwd of the fused kernel on [B,H,T,D] = (4, 16, 4096, 128)
    — ~O(T) memory where the einsum oracle would hold a 4096² score
    matrix per head.  Attention FLOPs: 4·B·H·T²·D per fwd, ×3.5 for
    fwd+bwd (dq, dk, dv re-use the two matmuls plus recompute).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    b, h, t, d = (int(os.environ.get("BENCH_ATTN_" + k, v)) for k, v in
                  (("B", 4), ("H", 16), ("T", 4096), ("D", 128)))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    platform = jax.devices()[0].platform
    device_kind = jax.devices()[0].device_kind
    if platform == "cpu" and "BENCH_ATTN_T" not in os.environ:
        t, steps = 512, 2

    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16 if platform != "cpu" else jnp.float32
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (b, h, t, d), dt) for i in range(3))

    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()
        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    l, _ = step(q, k, v)
    np.asarray(l)                       # completion barrier (PERF.md §1)
    t0 = time.perf_counter()
    for _ in range(steps):
        l, grads = step(q, k, v)
    np.asarray(l)
    dtime = time.perf_counter() - t0
    # causal halves the score matrix work
    flops = 3.5 * 4 * b * h * t * t * d / 2 * steps
    result = {
        "metric": "flash_attention_train_tflops",
        "value": round(flops / dtime / 1e12, 2),
        "unit": "TFLOP/s (B%d H%d T%d D%d causal %s fwd+bwd, 1 %s)"
                % (b, h, t, d, jnp.dtype(dt).name, platform),
        "vs_baseline": 0.0,  # no reference counterpart (2017, pre-attention)
        "ms_per_step": round(dtime / steps * 1e3, 2),
    }
    peak = PEAK_FLOPS.get(device_kind)
    if peak:
        result["mfu"] = round(flops / dtime / peak, 3)
    print(json.dumps(result))


def main():
    if os.environ.get("BENCH_MODE") == "attention":
        bench_attention()
        return
    # bs 128 is the measured single-chip sweet spot on v5e (PERF.md:
    # 2379 img/s vs 2263 at bs 256, 2114 at bs 512)
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "3")))
    image = int(os.environ.get("BENCH_IMAGE", "224"))

    import numpy as np
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    device_kind = jax.devices()[0].device_kind
    if platform == "cpu" and "BENCH_BATCH" not in os.environ:
        batch, steps = 16, 4  # keep the CPU smoke test fast

    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.block import functionalize

    net = vision.resnet50_v1()
    net.initialize()
    x0 = jnp.zeros((batch, 3, image, image), jnp.float32)
    fn, params = functionalize(net, x0, train=True)
    n_aux = fn.num_aux
    n_diff = len(params) - n_aux
    diff_params = params[:n_diff]
    aux_params = params[n_diff:]
    mom = [jnp.zeros_like(p) for p in diff_params]

    # mixed precision: bf16 activations/weights on the MXU, fp32 master
    # weights + fp32 update (the reference's mp_sgd fp16 recipe,
    # src/operator/optimizer_op.cc; BENCH_DTYPE=float32 opts out)
    bench_dtype = os.environ.get(
        "BENCH_DTYPE", "bfloat16" if platform != "cpu" else "float32")
    if bench_dtype not in ("bfloat16", "float32"):
        raise ValueError("BENCH_DTYPE must be bfloat16 or float32, got %r"
                         % bench_dtype)
    cdt = jnp.bfloat16 if bench_dtype == "bfloat16" else jnp.float32

    def loss_fn(diff, aux, x, y, rng):
        cdiff = [p.astype(cdt) for p in diff]
        (logits,), new_aux = fn(cdiff + list(aux), x.astype(cdt), rng=rng)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
        return loss, new_aux

    # donate params/aux/momentum: the step updates them in place in HBM
    # (PlanMemory's inplace discipline, done by XLA buffer donation)
    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(diff, aux, mom, x, y, rng):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(diff, aux, x, y, rng)
        new_mom = [0.9 * m - 0.05 * g.astype(jnp.float32)
                   for m, g in zip(mom, grads)]
        new_diff = [p + m for p, m in zip(diff, new_mom)]
        return new_diff, list(new_aux), new_mom, loss

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (batch, 3, image, image), jnp.float32)
    y = jax.random.randint(key, (batch,), 0, 1000)

    # Per-step training FLOPs for the MFU report.  Analytic by default:
    # ResNet-50 forward at 224² is 4.089 GMACs (stem+4 stages+fc, standard
    # count) → 8.18 GFLOPs; training ≈ 3× forward (one fwd + two bwd
    # matmul passes) = 24.5 GFLOPs/img, scaled by the spatial area.
    # BENCH_COST_ANALYSIS=1 uses XLA's own count instead (an AOT
    # lower().compile() — it bypasses the jit compile cache and is
    # extremely slow through the axon tunnel, so it is opt-in; XLA counts
    # ~22.5 GFLOPs/img for this program, 8% under the analytic figure).
    if os.environ.get("BENCH_COST_ANALYSIS") == "1":
        ca = train_step.lower(diff_params, aux_params, mom, x, y,
                              key).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        step_flops = float(ca.get("flops", 0.0)) or None
    else:
        step_flops = 3 * 2 * 4.089e9 * batch * (image / 224.0) ** 2

    for i in range(warmup):
        diff_params, aux_params, mom, loss = train_step(
            diff_params, aux_params, mom, x, y, jax.random.fold_in(key, i))
    np.asarray(loss)  # completion barrier (see module docstring)

    t0 = time.perf_counter()
    for i in range(steps):
        diff_params, aux_params, mom, loss = train_step(
            diff_params, aux_params, mom, x, y, jax.random.fold_in(key, i))
    np.asarray(loss)  # forces the whole donated-param chain
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    result = {
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s (bs %d, %dx%d, %s, 1 %s device)" % (
            batch, image, image, bench_dtype, platform),
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }
    if step_flops:
        tflops = step_flops * steps / dt / 1e12
        result["tflops"] = round(tflops, 1)
        peak = PEAK_FLOPS.get(device_kind)
        if peak:
            result["mfu"] = round(step_flops * steps / dt / peak, 3)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
