#!/usr/bin/env python
"""Kill stray distributed-training worker processes.

Port of /root/reference/tools/kill-mxnet.py: the reference pkill'd
python processes running a given program across a hostfile via ssh.
Same shape here — local by default, per-host over ssh with a hostfile —
matching tools/launch.py's worker model (no server processes exist).

Usage:
  python tools/kill-mxnet.py                 # local workers
  python tools/kill-mxnet.py train.py        # local, matching program
  python tools/kill-mxnet.py -H hosts train.py   # over ssh
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


def _local_pids(pattern):
    out = subprocess.run(["ps", "-eo", "pid,command"], capture_output=True,
                         text=True).stdout
    pids = []
    me = os.getpid()
    for line in out.splitlines()[1:]:
        parts = line.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid, cmd = int(parts[0]), parts[1]
        if pid == me:
            continue
        # a worker: python process carrying the launcher's env contract
        # isn't visible in ps; match on the program like the reference did
        if "python" in cmd and pattern in cmd and "kill-mxnet" not in cmd:
            pids.append(pid)
    return pids


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="kill distributed workers (reference tools/kill-mxnet.py)")
    parser.add_argument("program", nargs="?", default="",
                        help="match processes whose command contains this "
                        "(default: any MXTPU worker python)")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="kill on every host in this file via ssh")
    args = parser.parse_args(argv)
    pattern = args.program or "MXTPU"

    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
        for host in hosts:
            subprocess.run(
                ["ssh", "-o", "StrictHostKeyChecking=no", host,
                 "pkill -f %s || true" % (args.program or "MXTPU")])
            print("kill-mxnet: signalled workers on %s" % host)
        return 0

    pids = _local_pids(pattern)
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
            print("kill-mxnet: SIGTERM %d" % pid)
        except ProcessLookupError:
            pass
    if not pids:
        print("kill-mxnet: no matching workers")
    return 0


if __name__ == "__main__":
    sys.exit(main())
