#!/usr/bin/env python
"""Measure all-reduce bandwidth over the device mesh.

Port of /root/reference/tools/bandwidth/measure.py: the reference timed
KVStore push+pull of ResNet-sized gradient arrays across GPUs
(README.md:33-67, ~11 GB/s on 2 GPUs).  TPU-native, the gradient
all-reduce is ``jax.lax.psum`` over the mesh's data axis riding ICI; this
tool times exactly that collective and reports per-chip algorithm
bandwidth, the number BASELINE.json tracks.

busbw = algbw * 2 * (n-1) / n   (ring all-reduce traffic factor)

Usage:
  python tools/bandwidth/measure.py                 # all local devices
  python tools/bandwidth/measure.py --test-gpus 4   # first 4 devices
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/bandwidth/measure.py             # 8 fake devices
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def measure(num_devices=0, size_mb=256.0, num_arrays=30, iters=10,
            warmup=3, dtype="float32"):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = min(num_devices, len(devs)) if num_devices else len(devs)
    devs = devs[:n]
    mesh = Mesh(np.array(devs), ("dp",))

    itemsize = jnp.dtype(dtype).itemsize
    per_array = int(size_mb * 1e6 / num_arrays / itemsize)
    per_array = max(per_array - per_array % n, n)
    arrays = [jnp.ones((per_array,), dtype) for _ in range(num_arrays)]

    @jax.jit
    def allreduce(xs):
        def f(*xs):
            return tuple(jax.lax.psum(x, "dp") for x in xs)
        return shard_map(f, mesh=mesh, in_specs=(P("dp"),) * len(xs),
                         out_specs=(P(None),) * len(xs))(*xs)

    total_bytes = sum(a.nbytes for a in arrays)
    for _ in range(warmup):
        out = allreduce(tuple(arrays))
        jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = allreduce(tuple(arrays))
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    t = min(times)
    algbw = total_bytes / t / 1e9
    busbw = algbw * 2 * (n - 1) / n
    return {"devices": n, "size_mb": total_bytes / 1e6, "time_s": t,
            "algbw_GBps": algbw, "busbw_GBps": busbw}


def measure_kvstore(kv_type="dist_sync", size_mb=64.0, num_arrays=10,
                    iters=10, warmup=2, dtype="float32",
                    gc_type="none", gc_threshold=0.5):
    """Time KVStore push+pull per key batch — the user-facing path the
    reference README benchmarked (push grads, pull weights, ~11 GB/s on
    2 GPUs).  Run under tools/launch.py -n 2 for the dist path.
    gc_type='2bit' measures the quantized push path (pull still moves
    uncompressed weights; single-process stores quantize semantics only
    — the wire numbers are meaningful for dist stores)."""
    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kv.create(kv_type)
    if gc_type != "none":
        kv.set_gradient_compression({"type": gc_type,
                                     "threshold": gc_threshold})
    itemsize = np.dtype(dtype).itemsize
    per_array = max(1, int(size_mb * 1e6 / num_arrays / itemsize))
    keys = [str(i) for i in range(num_arrays)]
    vals = [mx.nd.ones((per_array,), dtype=dtype) for _ in keys]
    outs = [mx.nd.zeros((per_array,), dtype=dtype) for _ in keys]
    for k, v in zip(keys, vals):
        kv.init(k, v)
    total_bytes = sum(v._data.nbytes for v in vals)

    def roundtrip():
        kv.push(keys, [[v] for v in vals])
        kv.pull(keys, [[o] for o in outs])
        for o in outs:
            np.asarray(o._data[-1])  # completion barrier

    for _ in range(warmup):
        roundtrip()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        roundtrip()
        times.append(time.perf_counter() - t0)
    t = min(times)
    res = {"kv_type": kv_type, "workers": kv.num_workers,
           "num_keys": num_arrays, "total_mb": total_bytes / 1e6,
           "time_s": t, "GBps": total_bytes / t / 1e9,
           "per_key_GBps": total_bytes / num_arrays / t / 1e9}
    if gc_type != "none":
        res["gc_type"] = gc_type
        # the push wire carries 2-bit codes packed PER KEY: each key
        # contributes ceil(elements/4) bytes, independent of the
        # uncompressed dtype's width
        res["wire_bytes_per_push"] = num_arrays * (-(-per_array // 4))
    return res


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="all-reduce bandwidth over the mesh "
        "(reference tools/bandwidth/measure.py)")
    parser.add_argument("--test-gpus", "--test-devices", dest="devices",
                        type=int, default=0,
                        help="number of devices (0 = all)")
    parser.add_argument("--image-shape", default=None,
                        help="ignored (CLI compat)")
    parser.add_argument("--network", default=None,
                        help="ignored (CLI compat); sizes come from "
                        "--size-mb")
    parser.add_argument("--size-mb", type=float, default=256.0,
                        help="total gradient bytes per all-reduce")
    parser.add_argument("--num-arrays", type=int, default=30,
                        help="number of gradient arrays (ResNet-ish ~30 "
                        "large tensors)")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--kv-store", default=None,
                        help="measure through the KVStore API instead of "
                        "the raw mesh psum (e.g. 'device', 'dist_sync'; "
                        "run dist under tools/launch.py -n 2)")
    parser.add_argument("--gc-type", default="none",
                        help="gradient compression for the KVStore path "
                        "(none or 2bit)")
    parser.add_argument("--gc-threshold", type=float, default=0.5,
                        help="2bit compression threshold")
    args = parser.parse_args(argv)
    if args.kv_store:
        res = measure_kvstore(args.kv_store, args.size_mb,
                              args.num_arrays, args.iters,
                              dtype=args.dtype, gc_type=args.gc_type,
                              gc_threshold=args.gc_threshold)
        extra = " gc=%s push-wire=%.1f MB" % (
            res["gc_type"], res["wire_bytes_per_push"] / 1e6) \
            if args.gc_type != "none" else ""
        print("kv=%s workers=%d keys=%d total=%.1f MB time=%.4f s "
              "agg=%.2f GB/s per-key=%.3f GB/s%s"
              % (res["kv_type"], res["workers"], res["num_keys"],
                 res["total_mb"], res["time_s"], res["GBps"],
                 res["per_key_GBps"], extra))
        return res
    res = measure(args.devices, args.size_mb, args.num_arrays, args.iters,
                  dtype=args.dtype)
    print("devices=%d total=%.1f MB time=%.4f s algbw=%.2f GB/s "
          "busbw=%.2f GB/s"
          % (res["devices"], res["size_mb"], res["time_s"],
             res["algbw_GBps"], res["busbw_GBps"]))
    return res


if __name__ == "__main__":
    main()
