#!/usr/bin/env python
"""Generate the committed pretrained-fixture artifacts.

The reference pins inference numerics with downloaded pretrained models
plus expected outputs (tests/python/gpu/test_forward.py +
gluon/model_zoo/model_store.py).  This repo is egress-free, so the
equivalent is generated ONCE by this script and committed:

    tests/fixtures/squeezenet_tiny.params  + squeezenet_tiny_logits.npy
    tests/fixtures/gpt2_tiny.params        + gpt2_tiny_logits.npy

tests/test_pretrained_fixture.py rebuilds the deterministic input from
the same seeds, loads the checkpoint through the standard V2 path, and
asserts the logits — so ANY change to an op lowering, layer math, or
the serialization format that silently shifts inference shows up as a
cross-round regression.  Regenerate (and re-commit, with a note in the
commit message) only when an INTENTIONAL numerics change lands.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

FIXDIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures")


def fixture_inputs():
    """The deterministic inputs the regression test replays (kept in
    one place so generator and test cannot drift)."""
    import numpy as np
    rng = np.random.RandomState(1234)
    img = rng.randn(4, 3, 64, 64).astype(np.float32)
    toks = rng.randint(0, 256, (2, 32)).astype(np.int32)
    return img, toks


def _train_squeezenet():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.squeezenet1_1(classes=10)
    net.initialize(mx.init.Xavier(rnd_type="gaussian", magnitude=2))
    rng = np.random.RandomState(7)
    x = mx.nd.array(rng.randn(16, 3, 64, 64).astype(np.float32))
    y = mx.nd.array((rng.rand(16) * 10).astype(np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    for i in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(16)
    return net


def _train_gpt():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon.model_zoo import gpt

    net = gpt.gpt2_tiny()
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(8)
    toks = mx.nd.array(rng.randint(0, 256, (4, 32)), dtype="int32")
    tgts = mx.nd.array(rng.randint(0, 256, (4, 32)), dtype="int32")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1,
                                                 sparse_label=True)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    for i in range(5):
        with autograd.record():
            loss = loss_fn(net(toks), tgts).mean()
        loss.backward()
        trainer.step(4)
    return net


def main():
    import numpy as np
    import jax
    jax.config.update("jax_default_matmul_precision", "float32")
    import mxnet_tpu as mx

    os.makedirs(FIXDIR, exist_ok=True)
    img, toks = fixture_inputs()

    net = _train_squeezenet()
    net.save_params(os.path.join(FIXDIR, "squeezenet_tiny.params"))
    logits = net(mx.nd.array(img)).asnumpy()
    np.save(os.path.join(FIXDIR, "squeezenet_tiny_logits.npy"), logits)
    print("squeezenet_tiny: logits", logits.shape,
          "mean %.6f" % logits.mean())

    net = _train_gpt()
    net.save_params(os.path.join(FIXDIR, "gpt2_tiny.params"))
    logits = net(mx.nd.array(toks, dtype="int32")).asnumpy()
    np.save(os.path.join(FIXDIR, "gpt2_tiny_logits.npy"), logits)
    print("gpt2_tiny: logits", logits.shape, "mean %.6f" % logits.mean())


if __name__ == "__main__":
    main()
