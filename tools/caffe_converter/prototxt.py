"""Text-proto (prototxt) parser.

Parses Caffe's text format into nested dicts: `key: value` scalars and
`name { ... }` sub-messages; repeated keys collect into lists.  No
schema — the converter reads the keys it knows.
"""
from __future__ import annotations

import re

__all__ = ["parse_prototxt"]

_TOKEN = re.compile(r"""
    \s*(?:
        (?P<comment>\#[^\n]*) |
        (?P<brace>[{}]) |
        (?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<sep>:)? |
        (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*') |
        (?P<number>-?\d+\.?\d*(?:[eE][+-]?\d+)?) |
        (?P<punct>[,;])
    )""", re.VERBOSE)


def _tokens(text):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise ValueError("prototxt parse error at: %r"
                                 % text[pos:pos + 40])
            return
        pos = m.end()
        if m.group("comment") is not None or m.group("punct") is not None:
            continue
        if m.group("key") is not None:
            # m.lastgroup would report 'sep' when the colon matched too
            yield ("key" if m.group("sep") else "bare"), m.group("key")
        elif m.group("brace") is not None:
            yield "brace", m.group("brace")
        elif m.group("string") is not None:
            yield "string", m.group("string")
        else:
            yield "number", m.group("number")


def _coerce(tok_type, tok):
    if tok_type == "string":
        return tok[1:-1]
    if tok_type == "number":
        f = float(tok)
        return int(f) if f.is_integer() and "." not in tok \
            and "e" not in tok.lower() else f
    # bare identifier: bool or enum name
    if tok == "true":
        return True
    if tok == "false":
        return False
    return tok


def _add(msg, key, value):
    if key in msg:
        cur = msg[key]
        if not isinstance(cur, list):
            msg[key] = [cur]
        msg[key].append(value)
    else:
        msg[key] = value


def parse_prototxt(text):
    stack = [{}]
    pending_key = None
    toks = list(_tokens(text))
    i = 0
    while i < len(toks):
        t, v = toks[i]
        if t in ("key", "bare"):
            j = i + 1
            if j < len(toks) and toks[j][0] == "brace" and toks[j][1] == "{":
                sub = {}
                _add(stack[-1], v, sub)
                stack.append(sub)
                i = j + 1
                continue
            if t == "key":
                pending_key = v
                i += 1
                continue
            # bare identifier not opening a block: an enum/bool value
            if pending_key is None:
                raise ValueError("bare token %r with no key" % v)
            _add(stack[-1], pending_key, _coerce("bare", v))
            pending_key = None
            i += 1
            continue
        if t == "brace":
            if v == "}":
                stack.pop()
                if not stack:
                    raise ValueError("unbalanced braces")
            i += 1
            continue
        # value token following `key:`
        if pending_key is None:
            raise ValueError("value %r with no key" % v)
        _add(stack[-1], pending_key, _coerce(t, v))
        pending_key = None
        i += 1
    if len(stack) != 1:
        raise ValueError("unbalanced braces at EOF")
    return stack[0]


def as_list(value):
    if value is None:
        return []
    return value if isinstance(value, list) else [value]
