"""Caffe → mxnet_tpu converter.

TPU-native re-implementation of /root/reference/tools/caffe_converter/:
`convert_symbol` maps a deploy prototxt to a Symbol, `convert_model`
decodes a binary .caffemodel (a protobuf NetParameter) into
reference-format .params — with no caffe or protobuf dependency: the
prototxt is parsed as text-proto and the caffemodel through a minimal
protobuf wire-format reader (wire.py), using the field numbers from the
public caffe.proto schema.
"""
import importlib.util as _ilu
import os as _os
import sys as _sys

# the converter imports mxnet_tpu lazily; make the repo root importable
# when the tool is run straight from a checkout (find_spec only — do not
# initialize the framework/JAX just to probe importability)
if _ilu.find_spec("mxnet_tpu") is None:
    _sys.path.insert(0, _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)), "..", ".."))

from .convert_symbol import convert_symbol  # noqa: F401
from .convert_model import convert_model  # noqa: F401
