"""Caffe → mxnet_tpu converter.

TPU-native re-implementation of /root/reference/tools/caffe_converter/:
`convert_symbol` maps a deploy prototxt to a Symbol, `convert_model`
decodes a binary .caffemodel (a protobuf NetParameter) into
reference-format .params — with no caffe or protobuf dependency: the
prototxt is parsed as text-proto and the caffemodel through a minimal
protobuf wire-format reader (wire.py), using the field numbers from the
public caffe.proto schema.
"""
from .convert_symbol import convert_symbol  # noqa: F401
from .convert_model import convert_model  # noqa: F401
