"""Binary .caffemodel → reference-format .params.

Walks the protobuf wire format directly (wire.py) using the public
caffe.proto field numbers: NetParameter.layer = 100 (LayerParameter:
name = 1, type = 2, blobs = 7) with the V1 fallback NetParameter.layers
= 2 (V1LayerParameter: name = 4, blobs = 6); BlobProto: data = 5
(packed float), shape = 7 (BlobShape.dim = 1), legacy dims num/channels/
height/width = 1-4.  Weight-layout conversion: caffe InnerProduct
weights are (out, in) like FullyConnected; Convolution weights are
(out, in/group, kh, kw) in both; caffe BatchNorm blobs are
(mean, var, scale_factor) → moving_mean/var divided by the factor.
"""
from __future__ import annotations

import numpy as np

from . import wire

__all__ = ["convert_model"]


def _blob_array(blob_bytes):
    f = wire.decode_fields(blob_bytes)
    if 5 in f:
        chunks = []
        for chunk in f[5]:
            # packed (wire type 2) and unpacked (wire type 5) fixed32
            # both arrive as raw bytes from decode_fields
            if not isinstance(chunk, (bytes, bytearray)):
                raise ValueError(
                    "blob data field has unexpected varint encoding "
                    "(corrupt caffemodel?)")
            if len(chunk) % 4:
                raise ValueError(
                    "blob float data length %d is not a multiple of 4 "
                    "(file corrupt or truncated)" % len(chunk))
            chunks.append(np.frombuffer(chunk, "<f4"))
        # near zero-copy: real caffemodels hold tens of millions of floats
        arr = np.concatenate(chunks) if len(chunks) > 1 else \
            np.array(chunks[0], np.float32)
    else:
        arr = np.zeros((0,), np.float32)
    if 7 in f:
        shape_fields = wire.decode_fields(f[7][0])
        dims = [int(d) for d in shape_fields.get(1, [])]
    else:
        dims = [int(f.get(i, [0])[0]) for i in (1, 2, 3, 4)]
        dims = [d for d in dims if d] or [arr.size]
    return arr.reshape(dims)


# V1LayerParameter.LayerType enum values (public caffe.proto) → V2 names
V1_LAYER_TYPES = {
    1: "Accuracy", 3: "Concat", 4: "Convolution", 5: "Data",
    6: "Dropout", 8: "Flatten", 14: "InnerProduct", 15: "LRN",
    17: "Pooling", 18: "ReLU", 19: "Sigmoid", 20: "Softmax",
    21: "SoftmaxWithLoss", 22: "Split", 23: "TanH", 25: "Eltwise",
    33: "Slice", 35: "AbsVal", 36: "Silence", 39: "Deconvolution",
}


def _layers(model_bytes):
    """→ [(name, ltype, blobs, bottoms, tops)] for V2 and V1 messages."""
    net = wire.decode_fields(model_bytes)
    out = []
    for raw in net.get(100, []):      # LayerParameter
        f = wire.decode_fields(raw)
        name = f.get(1, [b""])[0].decode("utf-8")
        ltype = f.get(2, [b""])[0].decode("utf-8")
        blobs = [_blob_array(b) for b in f.get(7, [])]
        bottoms = [b.decode("utf-8") for b in f.get(3, [])]
        tops = [t.decode("utf-8") for t in f.get(4, [])]
        out.append((name, ltype, blobs, bottoms, tops))
    for raw in net.get(2, []):        # V1LayerParameter
        f = wire.decode_fields(raw)
        name = f.get(4, [b""])[0].decode("utf-8")
        code = int(f.get(5, [0])[0])
        ltype = V1_LAYER_TYPES.get(code, str(code))
        blobs = [_blob_array(b) for b in f.get(6, [])]
        bottoms = [b.decode("utf-8") for b in f.get(2, [])]
        tops = [t.decode("utf-8") for t in f.get(3, [])]
        out.append((name, ltype, blobs, bottoms, tops))
    return out


def convert_model(caffemodel_fname, output_prefix=None, epoch=0):
    """→ (arg_params, aux_params) dicts of numpy arrays; with
    output_prefix also writes `prefix-%04d.params` in the reference
    binary format (loadable by mx.model.load_checkpoint)."""
    with open(caffemodel_fname, "rb") as f:
        model_bytes = f.read()
    arg_params, aux_params = {}, {}
    prev_bn = None
    bn_by_top = {}  # tensor name -> BN layer that last wrote it
    for name, ltype, blobs, bottoms, tops in _layers(model_bytes):
        if ltype not in ("BatchNorm", "Scale"):
            # any intervening layer — even a parameter-free in-place
            # ReLU — breaks BN↔Scale pairing, exactly as convert_symbol's
            # made_by tracking does
            prev_bn = None
            for t in tops:
                bn_by_top.pop(t, None)
        if not blobs:
            continue
        if ltype == "BatchNorm":
            mean, var = blobs[0], blobs[1]
            factor = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 \
                else 1.0
            scale = 1.0 / factor if factor else 1.0
            aux_params[name + "_moving_mean"] = mean.reshape(-1) * scale
            aux_params[name + "_moving_var"] = var.reshape(-1) * scale
            prev_bn = name
            for t in tops:
                bn_by_top[t] = name
            continue
        if ltype == "Scale":
            # caffe splits BN into BatchNorm (stats) + Scale (gamma/beta);
            # the Symbol's BatchNorm learns gamma/beta itself, so a Scale
            # whose bottom IS a BatchNorm output stores under the BN
            # layer's name (matching convert_symbol's dataflow pairing);
            # file-order adjacency is the fallback when the caffemodel
            # carries no bottom fields
            bn_target = bn_by_top.get(bottoms[0]) if bottoms else prev_bn
            target = bn_target if bn_target is not None else name
            arg_params[target + "_gamma"] = blobs[0].reshape(-1)
            if len(blobs) > 1:
                arg_params[target + "_beta"] = blobs[1].reshape(-1)
            # the symbol's BatchNorm (BN-paired or standalone) always
            # lists a beta arg; a Scale without a bias blob (bias_term
            # defaults false) must still produce one for strict loading
            c = arg_params[target + "_gamma"].shape[0]
            arg_params.setdefault(target + "_beta", np.zeros(c, np.float32))
            if bn_target is None:
                # standalone Scale converts to BatchNorm with frozen unit
                # statistics (convert_symbol.py); supply them explicitly
                aux_params[target + "_moving_mean"] = np.zeros(c, np.float32)
                aux_params[target + "_moving_var"] = np.ones(c, np.float32)
            prev_bn = None
            for t in tops:
                bn_by_top.pop(t, None)
            continue
        if ltype == "PReLU":
            arg_params[name + "_gamma"] = blobs[0].reshape(-1)
        else:
            # Convolution/Deconvolution/InnerProduct: blob0 weight,
            # blob1 bias — layouts already match the framework's ops
            arg_params[name + "_weight"] = blobs[0]
            if len(blobs) > 1:
                arg_params[name + "_bias"] = blobs[1].reshape(-1)
    if output_prefix:
        import mxnet_tpu as mx
        save = {"arg:%s" % k: mx.nd.array(v)
                for k, v in arg_params.items()}
        save.update({"aux:%s" % k: mx.nd.array(v)
                     for k, v in aux_params.items()})
        mx.nd.save("%s-%04d.params" % (output_prefix, epoch), save)
    return arg_params, aux_params
