"""Binary .caffemodel → reference-format .params.

Walks the protobuf wire format directly (wire.py) using the public
caffe.proto field numbers: NetParameter.layer = 100 (LayerParameter:
name = 1, type = 2, blobs = 7) with the V1 fallback NetParameter.layers
= 2 (V1LayerParameter: name = 4, blobs = 6); BlobProto: data = 5
(packed float), shape = 7 (BlobShape.dim = 1), legacy dims num/channels/
height/width = 1-4.  Weight-layout conversion: caffe InnerProduct
weights are (out, in) like FullyConnected; Convolution weights are
(out, in/group, kh, kw) in both; caffe BatchNorm blobs are
(mean, var, scale_factor) → moving_mean/var divided by the factor.
"""
from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from . import wire

__all__ = ["convert_model"]


def _blob_array(blob_bytes):
    f = wire.decode_fields(blob_bytes)
    if 5 in f:
        data = []
        for chunk in f[5]:
            if isinstance(chunk, (bytes, bytearray)):
                data.extend(wire.packed_floats(chunk))
            else:  # unpacked fixed32 comes through as raw 4-byte values
                data.append(chunk)
        arr = np.asarray(data, np.float32)
    else:
        arr = np.zeros((0,), np.float32)
    if 7 in f:
        shape_fields = wire.decode_fields(f[7][0])
        dims = [int(d) for d in shape_fields.get(1, [])]
    else:
        dims = [int(f.get(i, [0])[0]) for i in (1, 2, 3, 4)]
        dims = [d for d in dims if d] or [arr.size]
    return arr.reshape(dims)


def _layers(model_bytes):
    net = wire.decode_fields(model_bytes)
    out = []
    for raw in net.get(100, []):      # LayerParameter
        f = wire.decode_fields(raw)
        name = f.get(1, [b""])[0].decode("utf-8")
        ltype = f.get(2, [b""])[0].decode("utf-8")
        blobs = [_blob_array(b) for b in f.get(7, [])]
        out.append((name, ltype, blobs))
    for raw in net.get(2, []):        # V1LayerParameter
        f = wire.decode_fields(raw)
        name = f.get(4, [b""])[0].decode("utf-8")
        ltype = str(f.get(5, [0])[0])
        blobs = [_blob_array(b) for b in f.get(6, [])]
        out.append((name, ltype, blobs))
    return out


def convert_model(caffemodel_fname, output_prefix=None, epoch=0):
    """→ (arg_params, aux_params) dicts of numpy arrays; with
    output_prefix also writes `prefix-%04d.params` in the reference
    binary format (loadable by mx.model.load_checkpoint)."""
    with open(caffemodel_fname, "rb") as f:
        model_bytes = f.read()
    arg_params, aux_params = {}, {}
    prev_bn = None
    for name, ltype, blobs in _layers(model_bytes):
        if not blobs:
            continue
        if ltype == "BatchNorm":
            mean, var = blobs[0], blobs[1]
            factor = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 \
                else 1.0
            scale = 1.0 / factor if factor else 1.0
            aux_params[name + "_moving_mean"] = mean.reshape(-1) * scale
            aux_params[name + "_moving_var"] = var.reshape(-1) * scale
            prev_bn = name
            continue
        if ltype == "Scale":
            # caffe splits BN into BatchNorm (stats) + Scale (gamma/beta);
            # the Symbol's BatchNorm learns gamma/beta itself, so a Scale
            # following a BatchNorm stores under the BN layer's name
            # (the reference converter does the same rename)
            target = prev_bn if prev_bn is not None else name
            arg_params[target + "_gamma"] = blobs[0].reshape(-1)
            if len(blobs) > 1:
                arg_params[target + "_beta"] = blobs[1].reshape(-1)
            prev_bn = None
            continue
        prev_bn = None
        if ltype == "PReLU":
            arg_params[name + "_gamma"] = blobs[0].reshape(-1)
        else:
            # Convolution/Deconvolution/InnerProduct: blob0 weight,
            # blob1 bias — layouts already match the framework's ops
            arg_params[name + "_weight"] = blobs[0]
            if len(blobs) > 1:
                arg_params[name + "_bias"] = blobs[1].reshape(-1)
    if output_prefix:
        import mxnet_tpu as mx
        save = {"arg:%s" % k: mx.nd.array(v)
                for k, v in arg_params.items()}
        save.update({"aux:%s" % k: mx.nd.array(v)
                     for k, v in aux_params.items()})
        mx.nd.save("%s-%04d.params" % (output_prefix, epoch), save)
    return arg_params, aux_params
