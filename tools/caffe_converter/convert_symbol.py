"""Deploy-prototxt → Symbol.

Original mapping of the caffe layer zoo the reference converter covered
(/root/reference/tools/caffe_converter/convert_symbol.py): Convolution,
Deconvolution, InnerProduct, Pooling (MAX/AVE, caffe's ceil-mode →
pooling_convention='full'), ReLU/TanH/Sigmoid/PReLU, LRN, Dropout,
Softmax(WithLoss), Flatten, Concat, Eltwise (sum/prod/max),
BatchNorm(+Scale folded), Crop, Reshape, AbsVal, Split.
"""
from __future__ import annotations

import os

from .prototxt import parse_prototxt, as_list

__all__ = ["convert_symbol"]

# V1 prototxts spell layer types as enum names (`layers { type: RELU }`);
# normalize to the V2 strings the dispatch below uses
V1_TYPE_NAMES = {
    "ABSVAL": "AbsVal", "ACCURACY": "Accuracy", "CONCAT": "Concat",
    "CONVOLUTION": "Convolution", "DECONVOLUTION": "Deconvolution",
    "DROPOUT": "Dropout", "ELTWISE": "Eltwise", "FLATTEN": "Flatten",
    "INNER_PRODUCT": "InnerProduct", "LRN": "LRN", "POOLING": "Pooling",
    "PRELU": "PReLU", "RELU": "ReLU", "RESHAPE": "Reshape",
    "SIGMOID": "Sigmoid", "SILENCE": "Silence", "SLICE": "Slice",
    "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "SPLIT": "Split", "TANH": "TanH",
}


def _ints(v, default=None, n=2):
    vals = as_list(v)
    if not vals:
        vals = [default]
    if len(vals) == 1:
        vals = vals * n
    return tuple(int(x) for x in vals[:n])


def _conv_args(p):
    kh, kw = None, None
    if "kernel_h" in p:
        kh, kw = int(p["kernel_h"]), int(p["kernel_w"])
    else:
        kh, kw = _ints(p.get("kernel_size"), 1)
    if "stride_h" in p:
        sh, sw = int(p["stride_h"]), int(p["stride_w"])
    else:
        sh, sw = _ints(p.get("stride"), 1)
    if "pad_h" in p:
        ph, pw = int(p["pad_h"]), int(p["pad_w"])
    else:
        ph, pw = _ints(p.get("pad"), 0)
    dil = _ints(p.get("dilation"), 1)
    return (kh, kw), (sh, sw), (ph, pw), dil


def convert_symbol(prototxt_fname_or_text):
    """→ (symbol, input_names).  Accepts a path or the prototxt text."""
    import mxnet_tpu as mx

    if os.path.exists(prototxt_fname_or_text):
        with open(prototxt_fname_or_text) as f:
            text = f.read()
    else:
        text = prototxt_fname_or_text
    net = parse_prototxt(text)
    layers = as_list(net.get("layer") or net.get("layers"))

    tops = {}
    made_by = {}  # top name -> layer type that produced it
    inputs = []
    for name in as_list(net.get("input")):
        tops[name] = mx.sym.Variable(name)
        inputs.append(name)

    def get(bname):
        if bname not in tops:
            tops[bname] = mx.sym.Variable(bname)
            inputs.append(bname)
        return tops[bname]

    for layer in layers:
        ltype = layer.get("type")
        ltype = V1_TYPE_NAMES.get(ltype, ltype)
        name = layer.get("name", "layer%d" % len(tops))
        bottoms = as_list(layer.get("bottom"))
        top_names = as_list(layer.get("top")) or [name]

        if ltype == "Input":
            for t in top_names:
                tops[t] = mx.sym.Variable(t)
                inputs.append(t)
            continue
        if ltype in ("Convolution", "Deconvolution"):
            p = layer.get("convolution_param", {})
            kernel, stride, pad, dil = _conv_args(p)
            op = mx.sym.Convolution if ltype == "Convolution" \
                else mx.sym.Deconvolution
            out = op(get(bottoms[0]), name=name, kernel=kernel,
                     stride=stride, pad=pad, dilate=dil,
                     num_filter=int(p.get("num_output", 0)),
                     num_group=int(p.get("group", 1)),
                     no_bias=not p.get("bias_term", True))
        elif ltype == "InnerProduct":
            p = layer.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                get(bottoms[0]), name=name,
                num_hidden=int(p.get("num_output", 0)),
                no_bias=not p.get("bias_term", True))
        elif ltype == "Pooling":
            p = layer.get("pooling_param", {})
            # caffe PoolMethod: 0 MAX, 1 AVE, 2 STOCHASTIC (no SUM);
            # stochastic approximated by max, as in the reference
            pool = {0: "max", "MAX": "max", 1: "avg", "AVE": "avg",
                    2: "max", "STOCHASTIC": "max"}[p.get("pool", "MAX")]
            if p.get("global_pooling"):
                out = mx.sym.Pooling(get(bottoms[0]), name=name,
                                     kernel=(1, 1), global_pool=True,
                                     pool_type=pool)
            else:
                kernel, stride, pad, _ = _conv_args(p)
                # caffe pooling output size uses ceil → 'full'
                out = mx.sym.Pooling(get(bottoms[0]), name=name,
                                     kernel=kernel, stride=stride,
                                     pad=pad, pool_type=pool,
                                     pooling_convention="full")
        elif ltype == "ReLU":
            out = mx.sym.Activation(get(bottoms[0]), name=name,
                                    act_type="relu")
        elif ltype == "TanH":
            out = mx.sym.Activation(get(bottoms[0]), name=name,
                                    act_type="tanh")
        elif ltype == "Sigmoid":
            out = mx.sym.Activation(get(bottoms[0]), name=name,
                                    act_type="sigmoid")
        elif ltype == "PReLU":
            out = mx.sym.LeakyReLU(get(bottoms[0]), name=name,
                                   act_type="prelu")
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = mx.sym.LRN(get(bottoms[0]), name=name,
                             alpha=float(p.get("alpha", 1e-4)),
                             beta=float(p.get("beta", 0.75)),
                             knorm=float(p.get("k", 1.0)),
                             nsize=int(p.get("local_size", 5)))
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            out = mx.sym.Dropout(get(bottoms[0]), name=name,
                                 p=float(p.get("dropout_ratio", 0.5)))
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            out = mx.sym.SoftmaxOutput(get(bottoms[0]), name=name)
        elif ltype == "Flatten":
            out = mx.sym.Flatten(get(bottoms[0]), name=name)
        elif ltype == "Concat":
            p = layer.get("concat_param", {})
            out = mx.sym.Concat(*[get(b) for b in bottoms], name=name,
                                dim=int(p.get("axis",
                                              p.get("concat_dim", 1))),
                                num_args=len(bottoms))
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op = p.get("operation", "SUM")
            coeff = [float(c) for c in as_list(p.get("coeff"))]
            if coeff and any(c != 1.0 for c in coeff):
                raise NotImplementedError(
                    "Eltwise coeff %s (layer %r): weighted sums are not "
                    "supported — rewrite as explicit scale layers"
                    % (coeff, name))
            syms = [get(b) for b in bottoms]
            out = syms[0]
            for s in syms[1:]:
                if op in ("SUM", 1):
                    out = out + s
                elif op in ("PROD", 0):
                    out = out * s
                else:
                    out = mx.sym.broadcast_maximum(out, s)
        elif ltype == "BatchNorm":
            p = layer.get("batch_norm_param", {})
            out = mx.sym.BatchNorm(
                get(bottoms[0]), name=name,
                eps=float(p.get("eps", 1e-5)), fix_gamma=False,
                use_global_stats=bool(p.get("use_global_stats", True)))
        elif ltype == "Scale":
            if made_by.get(bottoms[0]) == "BatchNorm":
                # caffe pairs BatchNorm with a Scale layer; BatchNorm here
                # already learns gamma/beta, so Scale folds into identity
                # (convert_model renames its blobs under the BN layer)
                out = mx.sym.identity(get(bottoms[0]), name=name)
            else:
                # standalone Scale: per-channel (axis=1) learned
                # gamma*x+beta.  That is exactly BatchNorm with frozen
                # unit statistics (mean=0, var=1, eps=0), which also
                # names its params {name}_gamma/{name}_beta — matching
                # what convert_model stores for the Scale blobs.  A
                # scale_param without bias_term leaves beta at its
                # zero default.
                p = layer.get("scale_param", {})
                if len(bottoms) > 1:
                    raise NotImplementedError(
                        "Scale layer %r with a second bottom supplying "
                        "the scale values is not supported — only "
                        "learned per-channel scales" % name)
                if int(p.get("axis", 1)) != 1:
                    raise NotImplementedError(
                        "Scale layer %r with axis=%s: only the channel "
                        "axis (1) is supported" % (name, p.get("axis")))
                out = mx.sym.BatchNorm(
                    get(bottoms[0]), name=name, eps=0.0,
                    fix_gamma=False, use_global_stats=True)
        elif ltype == "Crop":
            out = mx.sym.Crop(get(bottoms[0]), get(bottoms[1]),
                              name=name, num_args=2)
        elif ltype == "Reshape":
            p = layer.get("reshape_param", {}).get("shape", {})
            dims = tuple(int(d) for d in as_list(p.get("dim")))
            out = mx.sym.Reshape(get(bottoms[0]), name=name, shape=dims)
        elif ltype == "AbsVal":
            out = mx.sym.abs(get(bottoms[0]), name=name)
        elif ltype in ("Split", "Accuracy", "Silence"):
            out = get(bottoms[0]) if bottoms else None
        else:
            raise NotImplementedError(
                "caffe layer type %r (layer %r) is not supported"
                % (ltype, name))
        if out is not None:
            for t in top_names:
                tops[t] = out
                made_by[t] = ltype

    # output = last layer top that produced a symbol (Silence/Accuracy
    # tails have no top)
    last = None
    for layer in reversed(layers):
        for t in as_list(layer.get("top")):
            if t in tops:
                last = t
                break
        if last:
            break
    if last is None:
        raise ValueError("prototxt defines no output layer")
    return tops[last], inputs
