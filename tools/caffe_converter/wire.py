"""Minimal protobuf wire-format reader.

Decodes a serialized message into {field_number: [values]} without any
schema compilation: varints stay ints, length-delimited fields stay raw
bytes (the caller descends into sub-messages it knows, per the public
caffe.proto field numbers), fixed32 floats are returned raw for the
caller to unpack.  Enough to walk NetParameter → layer → blobs → data.
"""
from __future__ import annotations

__all__ = ["decode_fields", "varint"]


def varint(buf, pos):
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated protobuf: varint runs past end of "
                             "buffer (file corrupt or partially downloaded)")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def decode_fields(buf):
    """→ {field_number: [value, ...]} for one message's bytes.

    wire type 0 → int; 1 → 8 raw bytes; 2 → bytes; 5 → 4 raw bytes.
    """
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = varint(buf, pos)
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            val = None
        if wtype in (1, 2, 5) and pos > n:
            raise ValueError(
                "truncated protobuf: field %d (wire type %d) needs %d "
                "bytes past end of buffer (file corrupt or partially "
                "downloaded)" % (fnum, wtype, pos - n))
        if val is None:
            raise ValueError("unsupported wire type %d (field %d)"
                             % (wtype, fnum))
        fields.setdefault(fnum, []).append(val)
    return fields
