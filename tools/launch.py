#!/usr/bin/env python
"""Launch a distributed training job.

Port of /root/reference/tools/launch.py, re-targeted: the reference
spawned ps-lite scheduler/server/worker processes through dmlc_tracker
(ssh/mpi/sge/yarn, launch.py:59-84); the TPU-native framework has no
server processes — every worker is a JAX process in one collective mesh.

Launchers:
- ``local``: spawn N worker processes on this host wired together with
  ``jax.distributed`` (coordinator on 127.0.0.1).  Each worker sees the
  env contract DMLC_ROLE=worker, DMLC_NUM_WORKER, DMLC_WORKER_ID (kept
  for script compat) plus JAX_* coordination vars.  This is the
  reference's `--launcher local` fake-cluster test mode
  (tests/nightly/dist_sync_kvstore.py workflow).
- ``ssh``: run one worker per host from `-H hostfile` via ssh, pointing
  all of them at this host's coordinator port.
- On real TPU pods, prefer the platform launcher (GKE/queued resources):
  every pod VM already runs one process; pass --use-env-ranks to adopt
  the platform-provided rank env instead of spawning.
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_local_once(args, cmd, attempt):
    """One job attempt: spawn N workers, watch for failures.

    Failure detection (the collective-era replacement for ps-lite's
    server heartbeat/recovery hooks, reference src/kvstore/
    kvstore_dist.h:59-62): a worker dying strands its peers inside a
    collective, so the launcher — not the survivors — detects the death,
    tears the whole job down, and reports the failed rank.  Recovery is
    full job restart from checkpoints (launch_local --max-restarts).
    """
    import time
    port = args.port or _free_port()
    coordinator = "127.0.0.1:%d" % port
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            # JAX multi-process coordination
            "MXTPU_COORDINATOR": coordinator,
            "MXTPU_NUM_WORKERS": str(args.num_workers),
            "MXTPU_WORKER_RANK": str(rank),
            "MXTPU_RESTART_ATTEMPT": str(attempt),
            # reference env contract (dmlc_tracker) for script compat
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
        })
        if args.cpu_fake_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        if args.local_device_count:
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = ("%s --xla_force_host_platform_device_count"
                                "=%d" % (flags,
                                         args.local_device_count)).strip()
        procs.append(subprocess.Popen(cmd, env=env))
    try:
        while True:
            running = False
            for rank, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running = True
                elif rc != 0:
                    # one worker died — peers may be stranded in a
                    # collective; kill the job
                    print("launch.py: worker %d exited with %d; "
                          "terminating remaining workers" % (rank, rc),
                          file=sys.stderr, flush=True)
                    for q in procs:
                        if q.poll() is None:
                            q.kill()
                    for q in procs:
                        q.wait()
                    return rank, rc
            if not running:
                return None, 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            p.wait()
        return -1, 1


def classify_exit(rc):
    """Classify a failed worker's exit code → ('retryable'|'permanent',
    reason).

    Restart attempts are a scarce budget; burning one on a failure that
    will repeat identically (CLI misuse exit 2, unresolvable/unrunnable
    command 126/127) just delays the terminal error.  Deaths by signal
    (rc < 0: OOM-killer SIGKILL, preemption SIGTERM, segfaults) and
    generic runtime failures (rc == 1: an uncaught exception
    mid-training) are exactly what checkpoint-restart exists for.  Note
    the interpreter exits 1 for uncaught ImportError too — exit codes
    cannot distinguish an import-time crash from a mid-training one, so
    those retry conservatively (bounded by the backoff schedule)."""
    if rc < 0:
        return "retryable", "killed by signal %d" % (-rc)
    if rc == 2:
        return "permanent", ("exit code 2: usage/import-time error — "
                             "would fail identically on every attempt")
    if rc in (126, 127):
        return "permanent", "exit code %d: command not runnable" % rc
    return "retryable", "exit code %d: runtime failure" % rc


def launch_local(args, cmd):
    import time
    if args.dry_run:
        port = args.port or _free_port()
        for rank in range(args.num_workers):
            envs = ("MXTPU_COORDINATOR=127.0.0.1:%d MXTPU_NUM_WORKERS=%d "
                    "MXTPU_WORKER_RANK=%d DMLC_ROLE=worker "
                    "DMLC_NUM_WORKER=%d DMLC_WORKER_ID=%d"
                    % (port, args.num_workers, rank, args.num_workers,
                       rank))
            print("%s %s" % (envs,
                             " ".join(shlex.quote(c) for c in cmd)))
        return 0
    for attempt in range(args.max_restarts + 1):
        failed_rank, rc = _run_local_once(args, cmd, attempt)
        if failed_rank is None:
            return 0
        if failed_rank == -1 or attempt == args.max_restarts:
            return rc or 1
        kind, reason = classify_exit(rc)
        print("launch.py: worker %d failure classified %s (%s)"
              % (failed_rank, kind, reason), file=sys.stderr, flush=True)
        if kind == "permanent":
            print("launch.py: not restarting — failure is not retryable "
                  "(%d restart attempts preserved)"
                  % (args.max_restarts - attempt),
                  file=sys.stderr, flush=True)
            return rc or 1
        # exponential backoff: crash loops (a flaky host, a wedged
        # coordinator port) get geometrically more breathing room
        delay = min(args.restart_backoff * (2 ** attempt),
                    args.restart_backoff_max)
        if delay > 0:
            print("launch.py: backing off %.2fs before restart" % delay,
                  file=sys.stderr, flush=True)
            time.sleep(delay)
        print("launch.py: restarting job from checkpoints "
              "(attempt %d/%d) after worker %d failure"
              % (attempt + 1, args.max_restarts, failed_rank),
              file=sys.stderr, flush=True)
    return 1


def _ssh_commands(args, cmd):
    """→ [ssh argv per worker] — one worker per hostfile entry."""
    assert args.hostfile, "--launcher ssh requires -H hostfile"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    hosts = (hosts * args.num_workers)[:args.num_workers]
    port = args.port or _free_port()
    coordinator = "%s:%d" % (socket.gethostname(), port)
    out = []
    for rank, host in enumerate(hosts):
        envs = ("MXTPU_COORDINATOR=%s MXTPU_NUM_WORKERS=%d "
                "MXTPU_WORKER_RANK=%d DMLC_ROLE=worker DMLC_NUM_WORKER=%d "
                "DMLC_WORKER_ID=%d"
                % (shlex.quote(coordinator), args.num_workers, rank,
                   args.num_workers, rank))
        remote = "cd %s; %s %s" % (shlex.quote(os.getcwd()), envs,
                                   " ".join(shlex.quote(c) for c in cmd))
        out.append(["ssh", "-o", "StrictHostKeyChecking=no", host,
                    remote])
    return out


def launch_ssh(args, cmd):
    argvs = _ssh_commands(args, cmd)
    if args.dry_run:
        for argv in argvs:
            print(" ".join(shlex.quote(a) for a in argv))
        return 0
    procs = [subprocess.Popen(argv) for argv in argvs]
    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    return code


def _mpi_command(args, cmd):
    """One mpirun invocation (Open MPI CLI: -x/--hostfile); ranks adopt
    their mpirun-assigned rank at startup (base.py maps
    OMPI_COMM_WORLD_RANK/PMI_RANK/... onto the worker-rank contract the
    same way the reference's dmlc_tracker mpi mode rode mpirun,
    reference tools/launch.py:70).

    The coordinator must live where rank 0 runs: the first hostfile
    host (mpirun fills hosts in order), else this host.  Pass --port
    to pin a port known open on that host; _free_port() only checks
    the launcher."""
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.split()[0] for h in f if h.strip()]
        coord_host = hosts[0]
    else:
        coord_host = socket.gethostname()
    port = args.port or _free_port()
    coordinator = "%s:%d" % (coord_host, port)
    argv = ["mpirun", "-np", str(args.num_workers)]
    if args.hostfile:
        argv += ["--hostfile", args.hostfile]
    argv += ["-x", "MXTPU_COORDINATOR=%s" % coordinator,
             "-x", "MXTPU_NUM_WORKERS=%d" % args.num_workers,
             "-x", "MXTPU_RANK_FROM_MPI=1",
             "-x", "DMLC_ROLE=worker",
             "-x", "DMLC_NUM_WORKER=%d" % args.num_workers]
    return argv + list(cmd)


def launch_mpi(args, cmd):
    argv = _mpi_command(args, cmd)
    if args.dry_run:
        print(" ".join(shlex.quote(a) for a in argv))
        return 0
    return subprocess.call(argv)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference tools/launch.py)")
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="ignored — no parameter servers in the "
                        "all-reduce design (kept for CLI compat)")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for ssh launcher")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the launch commands/environment "
                        "without running anything")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi"],
                        help="cluster type")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    parser.add_argument("--cpu-fake-devices", action="store_true",
                        help="force JAX_PLATFORMS=cpu in workers (local "
                        "fake-cluster testing)")
    parser.add_argument("--local-device-count", type=int, default=0,
                        help="virtual devices per worker process "
                        "(xla_force_host_platform_device_count; test "
                        "multi-chip-per-host jobs without hardware)")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="restart the whole job this many times when "
                        "a worker dies (workers resume from their own "
                        "checkpoints; MXTPU_RESTART_ATTEMPT tells them "
                        "which attempt is running); non-retryable "
                        "failures (e.g. exit code 2) stop immediately")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds between restarts; doubles "
                        "each attempt (exponential backoff)")
    parser.add_argument("--restart-backoff-max", type=float, default=60.0,
                        help="backoff ceiling in seconds")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command for launching the program")
    args = parser.parse_args(argv)
    cmd = [c for c in args.command if c != "--"]
    assert cmd, "no command given"
    if args.launcher == "local":
        return launch_local(args, cmd)
    if args.launcher == "mpi":
        return launch_mpi(args, cmd)
    return launch_ssh(args, cmd)


if __name__ == "__main__":
    sys.exit(main())
