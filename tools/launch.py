#!/usr/bin/env python
"""Launch a distributed training job.

Port of /root/reference/tools/launch.py, re-targeted: the reference
spawned ps-lite scheduler/server/worker processes through dmlc_tracker
(ssh/mpi/sge/yarn, launch.py:59-84); the TPU-native framework has no
server processes — every worker is a JAX process in one collective mesh.

Launchers:
- ``local``: spawn N worker processes on this host wired together with
  ``jax.distributed`` (coordinator on 127.0.0.1).  Each worker sees the
  env contract DMLC_ROLE=worker, DMLC_NUM_WORKER, DMLC_WORKER_ID (kept
  for script compat) plus JAX_* coordination vars.  This is the
  reference's `--launcher local` fake-cluster test mode
  (tests/nightly/dist_sync_kvstore.py workflow).
- ``ssh``: run one worker per host from `-H hostfile` via ssh, pointing
  all of them at this host's coordinator port; monitored like local
  (first failure tears the job down, --max-restarts applies).

Failure handling: worker exits are classified retryable/permanent
(classify_exit) with exponential backoff between restarts; hangs are
caught by the per-rank heartbeat monitor (--heartbeat-timeout, files
touched by mxnet_tpu.watchdog under MXTPU_HEARTBEAT_DIR) and by the
in-process watchdog's stall exit code 75 — see ROBUSTNESS.md §5/§7.
Restarts warm-start: every attempt shares one AOT executable cache
(--aot-cache-dir → MXTPU_AOT_CACHE_DIR + jax's persistent compile
cache), so a restarted rank deserializes the compiled fit step instead
of paying trace+compile again — see PERF.md §12.

Elastic mode (``--elastic``, ROBUSTNESS.md §9): world size becomes a
per-restart decision.  Each worker slot (its stable identity across
attempts — hostfile line for ssh, original index locally) accumulates a
consecutive-failure count; when the same slot is blamed ``--evict-after``
times in a row, or its exit classifies permanent from attempt 1 on
(attempt-0 permanent failures still fail the job fast — a usage/import
error hits every rank identically), the next attempt drops
it — survivors are re-ranked contiguously (fresh
MXTPU_NUM_WORKERS/MXTPU_WORKER_RANK/DMLC_* exports, fresh coordinator
port) and resume from the newest complete checkpoint at N-1.  Evicted
slots sit out ``--readmit-after`` attempts, then rejoin (scale back up
toward ``-n``); ``--min-workers`` floors the shrink.  Every transition
is recorded in ``<run-dir>/membership.json``
(``tools/perf_probe/telemetry_report.py`` renders it).
Job-scope telemetry (``--telemetry-dir``, OBSERVABILITY.md §8): with a
run dir, every rank's JSON-lines telemetry stream (append-only per
slot), crash postmortem, and stall-stacks land in
``<run-dir>/telemetry/`` next to membership.json — one tree
``tools/perf_probe/job_report.py`` merges into a job timeline with
straggler blame and a cross-rank chrome trace.

- On real TPU pods, prefer the platform launcher (GKE/queued resources):
  every pod VM already runs one process; pass --use-env-ranks to adopt
  the platform-provided rank env instead of spawning.

Serving-fleet mode (``--serve``, SERVING.md §9): the command is run as
N INDEPENDENT serving-replica slots (tools/serve_worker.py) supervised
per-slot — serving has no collective, so one replica dying replaces
that replica instead of tearing the job down.  Exit 80 journals
drain/replace and respawns without blame; crashes/SIGKILL/stalls
respawn with backoff (AOT-warm via the shared cache) until
``--evict-after`` consecutive failures evict the slot; every
transition lands in ``<run-dir>/membership.json``.  Each slot
publishes ``<run-dir>/serve-port-slot<K>.json`` (the router-proxy
discovery + incarnation channel); ``<run-dir>/serve-stop`` stops the
fleet gracefully.
"""
from __future__ import annotations

import argparse
import json
import os
import shlex
import shutil
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time

# exit-code contract with mxnet_tpu/watchdog.py, mxnet_tpu/fault.py and
# mxnet_tpu/serving/replica.py (kept literal here: the launcher must
# work without the package importable on this host)
STALL_EXIT = 75         # EX_TEMPFAIL: watchdog stall — retryable
PORT_IN_USE_EXIT = 76   # coordinator port bind failure — retryable
WORKER_LOST_EXIT = 77   # worker.lost fault site: simulated permanent
                        # rank death — retryable; elastic mode evicts
SERVE_DRAIN_EXIT = 80   # graceful serving-replica drain — CLEAN: never
                        # blamed toward eviction; the restart spins an
                        # AOT-warm replacement (journaled drain/replace)


class _Membership:
    """Which worker slots are in the job, attempt by attempt.

    A *slot* is a worker's stable identity across the whole launch
    (locally its original index 0..n-1; over ssh its hostfile line), as
    opposed to its *rank*, the contiguous per-attempt index survivors
    are re-packed into.  Tracks per-slot consecutive-failure counts,
    evictions, and re-admissions, and journals every transition into
    ``<run-dir>/membership.json`` (schema ``mxtpu-membership-1``) so the
    job's shape over time survives the launcher process."""

    def __init__(self, args):
        self.total = args.num_workers
        self.active = list(range(args.num_workers))
        # consecutive-failure streak: only the LAST blamed slot can have
        # one (a failure blamed on any other slot resets it), so two
        # scalars state the invariant a per-slot map would only obscure
        self.blamed_slot = None
        self.streak = 0
        self.evicted_at = {}     # slot -> attempt whose failure evicted it
        self.transitions = []
        self.path = None
        run_dir = getattr(args, "run_dir", None)
        if run_dir:
            self.path = os.path.join(run_dir, "membership.json")
        self.record(0, "launch")

    @property
    def world_size(self):
        return len(self.active)

    def slot_of(self, rank):
        """Map a per-attempt contiguous rank back to its stable slot."""
        if 0 <= rank < len(self.active):
            return self.active[rank]
        return rank

    def record(self, attempt, event, **extra):
        entry = {"time": time.time(), "attempt": attempt, "event": event,
                 "world_size": self.world_size,
                 "active_slots": list(self.active),
                 "evicted_slots": sorted(self.evicted_at)}
        entry.update(extra)
        self.transitions.append(entry)
        self._flush()

    def _flush(self):
        if not self.path:
            return
        doc = {"schema": "mxtpu-membership-1", "total_slots": self.total,
               "transitions": self.transitions}
        tmp = "%s.tmp-%d" % (self.path, os.getpid())
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.path)
        except OSError as e:  # the journal must never take the job down
            print("launch.py: could not write %s: %s" % (self.path, e),
                  file=sys.stderr, flush=True)

    def note_failure(self, attempt, rank, rc, kind, reason):
        """Blame ``rank``'s slot for this attempt's failure; the streak
        is *consecutive* — a failure blamed on a different slot restarts
        it at 1.  Returns the blamed slot."""
        slot = self.slot_of(rank)
        self.streak = self.streak + 1 if slot == self.blamed_slot else 1
        self.blamed_slot = slot
        self.record(attempt, "failure", slot=slot, rank=rank, rc=rc,
                    kind=kind, reason=reason,
                    consecutive_failures=self.streak)
        return slot

    def evict(self, attempt, slot, reason):
        self.active.remove(slot)
        self.evicted_at[slot] = attempt
        self.record(attempt, "evict", slot=slot, reason=reason)

    def readmit_due(self, attempt, sit_out):
        """Evicted slots whose sit-out has elapsed by ``attempt``: a slot
        evicted after attempt k sits out attempts k+1..k+sit_out and is
        due again at k+sit_out+1."""
        return sorted(s for s, at in self.evicted_at.items()
                      if attempt > at + sit_out)

    def readmit(self, attempt, slot):
        del self.evicted_at[slot]
        if self.blamed_slot == slot:
            self.blamed_slot, self.streak = None, 0  # fresh on rejoin
        self.active = sorted(self.active + [slot])
        self.record(attempt, "readmit", slot=slot)


def _cache_env(args):
    """Warm-start env for workers: the AOT executable cache
    (mxnet_tpu.aot_cache — restarted ranks deserialize the compiled fit
    step instead of re-tracing + re-compiling it) plus jax's own
    persistent compilation cache as the fallback layer for every other
    program.  The dir is created once per launch invocation and reused
    across restart attempts — that persistence IS the feature.  Values
    already exported by the operator are never overridden."""
    if not getattr(args, "aot_cache_dir", None):
        return {}
    # Always export the resolved dir: main() already made the operator's
    # choice (explicit flag > their env > auto temp dir), and ssh workers
    # see ONLY this env string — the launcher's environment does not ride
    # along, so "already exported locally" must not suppress the export.
    # Operator-set jax cache knobs are forwarded verbatim for the same
    # reason; the min-compile-time default of 0 exists because jax's own
    # threshold (1s) would skip most of a small model's programs, and a
    # restart wants them all.
    return {
        "MXTPU_AOT_CACHE_DIR": args.aot_cache_dir,
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR") or
            os.path.join(args.aot_cache_dir, "xla"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS":
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0"),
    }


def _telemetry_env(args, slot):
    """Job-scope telemetry exports for one worker slot: a per-slot
    JSON-lines stream under ``<run-dir>/telemetry/`` plus the postmortem
    dir, so every rank's timeline, crash postmortem, and stall-stacks
    land in ONE tree next to membership.json (the input contract of
    tools/perf_probe/job_report.py).  Streams are keyed by SLOT, not
    rank: a slot's identity is stable across elastic re-rankings, the
    file is opened append-only by the worker, and every line carries the
    writing attempt's identity block — so attempt N's lines never
    overwrite attempt N-1's (schema mxtpu-telemetry-2).  Operator-set
    MXTPU_TELEMETRY / MXTPU_POSTMORTEM_DIR win (forwarded verbatim, for
    the same ssh-env reason as _cache_env)."""
    d = getattr(args, "telemetry_dir", None)
    if not d:
        return {}
    spec = os.environ.get("MXTPU_TELEMETRY")
    if not spec:
        spec = "%s:%s" % (os.path.join(d, "stream-slot%d.jsonl" % slot),
                          args.telemetry_interval)
    return {
        "MXTPU_TELEMETRY": spec,
        "MXTPU_POSTMORTEM_DIR":
            os.environ.get("MXTPU_POSTMORTEM_DIR") or d,
        # serving-scope layout (ISSUE 13): a Router in this slot
        # journals next to the replica streams (append-only per slot,
        # like the streams), so tools/perf_probe/serve_report.py finds
        # journal + streams + postmortems in ONE tree
        "MXTPU_SERVE_JOURNAL":
            os.environ.get("MXTPU_SERVE_JOURNAL") or
            os.path.join(d, "router-journal-slot%d.jsonl" % slot),
    }


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _escalate_kill(procs, first_sig=signal.SIGTERM, grace=5.0):
    """Tear a job down with bounded patience: ``first_sig`` → wait up to
    ``grace`` → SIGTERM → ``grace`` → SIGKILL, then reap.  Every stop
    path (worker death, heartbeat stall, Ctrl-C) routes through here, so
    a worker that ignores polite signals — or is the very wedged process
    we are killing *because* it stopped responding — can delay teardown
    by at most 2×grace, never forever."""
    seq = []
    for sig in (first_sig, signal.SIGTERM, signal.SIGKILL):
        if not seq or seq[-1] != sig:
            seq.append(sig)
    for sig in seq:
        alive = [p for p in procs if p.poll() is None]
        if not alive:
            break
        for p in alive:
            try:
                p.send_signal(sig)
            except OSError:
                pass  # exited between poll and signal
        if sig == signal.SIGKILL:
            break
        deadline = time.time() + grace
        while time.time() < deadline and \
                any(p.poll() is None for p in procs):
            time.sleep(0.05)
    # bounded reap: even SIGKILL cannot collect a process stuck in
    # uninterruptible sleep (D-state — the hung-NFS case this defense
    # targets); waiting forever here would convert a detected worker
    # hang into an undetected launcher hang
    deadline = time.time() + max(grace, 5.0)
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            print("launch.py: giving up reaping pid %d (uninterruptible "
                  "sleep?); continuing teardown" % p.pid,
                  file=sys.stderr, flush=True)
        except Exception:
            pass


def _monitor_procs(args, procs, heartbeat_dir=None, label="worker"):
    """Watch a running job; returns ``(failed_rank, rc)`` — (None, 0) on
    clean completion, rank+code on the first failure (the job is torn
    down first), (-1, 1) on Ctrl-C.

    Two failure channels (the collective-era replacement for ps-lite's
    server heartbeat/recovery hooks, reference src/kvstore/
    kvstore_dist.h:59-62):

    - **exit**: a worker dying strands its peers inside a collective, so
      the launcher — not the survivors — detects the death and kills the
      job.
    - **heartbeat silence** (``--heartbeat-timeout`` > 0): a worker that
      *hangs* — wedged in native code under the GIL, swapped out, so
      even its in-process watchdog can't run — stops touching its
      per-rank heartbeat file (written by mxnet_tpu.watchdog inside the
      worker).  A stale mtime past the deadline is treated as a stall:
      the job is killed and the rank reported with the stall exit code
      (75), which classify_exit maps to retryable.  Workers that never
      wrote a heartbeat (non-mxnet commands) are not monitored.
    """
    try:
        while True:
            running = False
            for rank, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running = True
                elif rc != 0:
                    # one worker died — peers may be stranded in a
                    # collective; kill the job (politely first: peers
                    # flush telemetry postmortems on SIGTERM)
                    print("launch.py: %s %d exited with %d; "
                          "terminating remaining workers"
                          % (label, rank, rc), file=sys.stderr,
                          flush=True)
                    _escalate_kill(procs, signal.SIGTERM,
                                   args.kill_grace)
                    return rank, rc
            if not running:
                return None, 0
            if heartbeat_dir and args.heartbeat_timeout > 0:
                now = time.time()
                for rank, p in enumerate(procs):
                    if p.poll() is not None:
                        continue
                    hb = os.path.join(heartbeat_dir,
                                      "hb-%d.json" % rank)
                    try:
                        age = now - os.stat(hb).st_mtime
                    except OSError:
                        continue  # never wrote one: not monitored
                    if age > args.heartbeat_timeout:
                        print("launch.py: %s %d heartbeat silent for "
                              "%.1fs (deadline %.1fs) — declaring the "
                              "rank stalled and terminating the job"
                              % (label, rank, age,
                                 args.heartbeat_timeout),
                              file=sys.stderr, flush=True)
                        _escalate_kill(procs, signal.SIGTERM,
                                       args.kill_grace)
                        return rank, STALL_EXIT
            time.sleep(0.2)
    except KeyboardInterrupt:
        # bounded Ctrl-C teardown: SIGINT first (KeyboardInterrupt in
        # the worker → its finally blocks / atexit postmortems run),
        # then the escalation ladder — never an unbounded wait() on a
        # worker that swallows the signal
        print("launch.py: interrupt — stopping workers (SIGINT, then "
              "escalating after %.1fs grace)" % args.kill_grace,
              file=sys.stderr, flush=True)
        _escalate_kill(procs, signal.SIGINT, args.kill_grace)
        return -1, 1


def _worker_env(args, mem, world, rank, slot, attempt, prev_world):
    """The per-worker env contract for one attempt.  ``rank`` is the
    contiguous per-attempt index (what jax.distributed and DMLC_* see);
    ``slot`` is the launch-stable identity elastic eviction tracks —
    equal until a membership change re-packs the survivors."""
    env = {
        "MXTPU_NUM_WORKERS": str(world),
        "MXTPU_WORKER_RANK": str(rank),
        "MXTPU_WORKER_SLOT": str(slot),
        "MXTPU_RESTART_ATTEMPT": str(attempt),
        # lets a restarted worker count the cross-attempt world change
        # in its elastic.transitions telemetry (mxnet_tpu/elastic.py).
        # Always set — "" reads as unset — so a stale value inherited
        # from the launcher's own environment (nested launch, debug
        # shell reusing a worker env) can't fabricate a transition.
        "MXTPU_PREV_WORLD_SIZE":
            "" if prev_world is None else str(prev_world),
        # reference env contract (dmlc_tracker) for script compat
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(world),
        "DMLC_NUM_SERVER": "0",
        "DMLC_WORKER_ID": str(rank),
    }
    env.update(_cache_env(args))
    env.update(_telemetry_env(args, slot))
    return env


def _run_local_once(args, cmd, attempt, mem, prev_world=None):
    """One local job attempt: spawn the active workers wired to a fresh
    coordinator port (``--port 0`` re-picks per attempt, so a port left
    wedged by the previous attempt is simply abandoned) plus a fresh
    heartbeat run dir, then monitor to completion or teardown."""
    port = args.port or _free_port()
    coordinator = "127.0.0.1:%d" % port
    hb_dir = tempfile.mkdtemp(prefix="mxtpu-hb-")
    world = mem.world_size
    mem.record(attempt, "attempt_start", port=port)
    procs = []
    try:
        for rank, slot in enumerate(mem.active):
            env = dict(os.environ)
            env.update(_worker_env(args, mem, world, rank, slot,
                                   attempt, prev_world))
            env.update({
                # JAX multi-process coordination
                "MXTPU_COORDINATOR": coordinator,
                # per-rank heartbeat files — exported even when
                # --heartbeat-timeout is 0: the files are the "where
                # was it" record on any kill, and the worker watchdog's
                # stall diagnostics fall back to this dir when
                # MXTPU_POSTMORTEM_DIR is unset (cost: one small write
                # per worker per second)
                "MXTPU_HEARTBEAT_DIR": hb_dir,
            })
            if args.cpu_fake_devices:
                env["JAX_PLATFORMS"] = "cpu"
                env.pop("PALLAS_AXON_POOL_IPS", None)
            if args.local_device_count:
                flags = env.get("XLA_FLAGS", "")
                env["XLA_FLAGS"] = (
                    "%s --xla_force_host_platform_device_count"
                    "=%d" % (flags, args.local_device_count)).strip()
            procs.append(subprocess.Popen(cmd, env=env))
        return _monitor_procs(args, procs, heartbeat_dir=hb_dir)
    finally:
        # a stalled worker without MXTPU_POSTMORTEM_DIR falls back to
        # dumping its stack trace / postmortem HERE — deleting those
        # would erase the diagnosis the stall exit just promised
        try:
            diagnostics = [n for n in os.listdir(hb_dir)
                           if n.startswith(("stall-stacks-",
                                            "postmortem-"))]
        except OSError:
            diagnostics = []
        if diagnostics:
            print("launch.py: stall diagnostics preserved in %s (%s)"
                  % (hb_dir, ", ".join(sorted(diagnostics))),
                  file=sys.stderr, flush=True)
        else:
            shutil.rmtree(hb_dir, ignore_errors=True)


def classify_exit(rc):
    """Classify a failed worker's exit code →
    ('retryable'|'permanent'|'clean', reason).

    Restart attempts are a scarce budget; burning one on a failure that
    will repeat identically (CLI misuse exit 2, unresolvable/unrunnable
    command 126/127) just delays the terminal error.  Deaths by signal
    (rc < 0: OOM-killer SIGKILL, preemption SIGTERM, segfaults) and
    generic runtime failures (rc == 1: an uncaught exception
    mid-training) are exactly what checkpoint-restart exists for.  Note
    the interpreter exits 1 for uncaught ImportError too — exit codes
    cannot distinguish an import-time crash from a mid-training one, so
    those retry conservatively (bounded by the backoff schedule).

    Two dedicated retryable classes from the hang-defense layer
    (mxnet_tpu/watchdog.py): 75 (EX_TEMPFAIL) is a diagnosed stall —
    the worker's watchdog dumped stacks + postmortem and self-terminated,
    or this launcher declared heartbeat silence; 76 is a coordinator
    port bind failure — a restart with ``--port 0`` picks a fresh port.

    One CLEAN class: 80 is a graceful serving-replica drain
    (mxnet_tpu/serving/replica.py EXIT_SERVE_DRAIN) — planned, never
    blamed toward elastic eviction; the restart loop journals it as
    drain/replace transitions and spins the replacement without
    backoff."""
    if rc < 0:
        return "retryable", "killed by signal %d" % (-rc)
    if rc == STALL_EXIT:
        return "retryable", ("exit code 75: stall (watchdog/heartbeat "
                             "detected a hang; stacks + postmortem "
                             "dumped)")
    if rc == PORT_IN_USE_EXIT:
        return "retryable", ("exit code 76: coordinator port in use — "
                             "restart re-picks the port (--port 0)")
    if rc == WORKER_LOST_EXIT:
        return "retryable", ("exit code 77: worker lost (fault site "
                             "worker.lost — simulated permanent rank "
                             "death; --elastic evicts repeat offenders)")
    if rc == SERVE_DRAIN_EXIT:
        return "clean", ("exit code 80: graceful serving drain — the "
                         "replica finished its residents and released "
                         "its pages; never blamed toward eviction, the "
                         "restart spins an AOT-warm replacement")
    if rc == 2:
        return "permanent", ("exit code 2: usage/import-time error — "
                             "would fail identically on every attempt")
    if rc in (126, 127):
        return "permanent", "exit code %d: command not runnable" % rc
    return "retryable", "exit code %d: runtime failure" % rc


def _restart_loop(args, run_once, cmd):
    """The classify → (evict/readmit) → backoff → restart-from-
    checkpoints policy, shared by the local and ssh launchers.  With
    ``--elastic`` the membership for each attempt is recomputed here:
    a slot blamed for ``--evict-after`` consecutive failures (or one
    permanent exit) is dropped and the survivors re-ranked; evicted
    slots rejoin after sitting out ``--readmit-after`` attempts."""
    mem = _Membership(args)
    elastic = getattr(args, "elastic", False)
    prev_world = None
    for attempt in range(args.max_restarts + 1):
        if elastic and attempt:
            for slot in mem.readmit_due(attempt, args.readmit_after):
                if mem.world_size >= args.num_workers:
                    break  # never above the launch size
                mem.readmit(attempt, slot)
                print("launch.py: re-admitting recovered worker slot %d "
                      "for attempt %d (world size back up to %d)"
                      % (slot, attempt, mem.world_size),
                      file=sys.stderr, flush=True)
        world = mem.world_size
        failed_rank, rc = run_once(args, cmd, attempt, mem, prev_world)
        if failed_rank is None:
            mem.record(attempt, "complete")
            return 0
        if failed_rank == -1:
            mem.record(attempt, "interrupted")
            return rc or 1
        kind, reason = classify_exit(rc)
        if kind == "clean":
            # graceful serving drain (exit 80): planned, never blamed —
            # no failure note, no streak, no eviction, no backoff.  The
            # journal records drain/replace DISTINCTLY from training
            # failures; the next attempt is the replacement spin-up
            # (AOT-warm via the shared --aot-cache-dir).
            slot = mem.slot_of(failed_rank)
            mem.record(attempt, "drain", slot=slot, rank=failed_rank,
                       rc=rc, reason=reason)
            print("launch.py: attempt %d (world size %d): worker rank "
                  "%d (slot %d) drained gracefully (%s)"
                  % (attempt, world, failed_rank, slot, reason),
                  file=sys.stderr, flush=True)
            if attempt == args.max_restarts:
                # out of restart budget: the drain itself is a success
                mem.record(attempt, "complete", rc=rc)
                return 0
            mem.record(attempt, "replace", slot=slot)
            print("launch.py: spinning replacement for drained slot %d "
                  "(attempt %d/%d; no backoff — a drain is planned, "
                  "not a crash)" % (slot, attempt + 1,
                                    args.max_restarts),
                  file=sys.stderr, flush=True)
            prev_world = world
            continue
        slot = mem.note_failure(attempt, failed_rank, rc, kind, reason)
        print("launch.py: attempt %d (world size %d): worker rank %d "
              "(slot %d) failure classified %s (%s)"
              % (attempt, world, failed_rank, slot, kind, reason),
              file=sys.stderr, flush=True)
        if attempt == args.max_restarts:
            mem.record(attempt, "gave_up", rc=rc)
            return rc or 1
        evicted_now = []
        if elastic:
            # a PERMANENT exit evicts only once the job has proven it
            # can run at all (attempt >= 1): exit codes cannot tell a
            # bad HOST from a bad COMMAND, and a usage/import error hits
            # every rank identically on the very first attempt — evicting
            # healthy slots one per attempt would burn the whole restart
            # budget re-proving it, so attempt-0 permanent failures fail
            # fast below (and must not slip through the streak branch
            # either — with --evict-after 1 a streak of 1 would).  A
            # host that goes permanently bad mid-job still gets dropped
            # on any later attempt.
            if kind == "permanent":
                should_evict = attempt > 0
            else:
                should_evict = mem.streak >= args.evict_after
            if should_evict and slot in mem.active:
                if world - 1 >= max(1, args.min_workers):
                    why = ("exit classified permanent" if
                           kind == "permanent" else
                           "%d consecutive failures (--evict-after %d)"
                           % (mem.streak, args.evict_after))
                    mem.evict(attempt, slot, why)
                    evicted_now.append(slot)
                    print("launch.py: evicting worker slot %d (%s); "
                          "next attempt runs at world size %d"
                          % (slot, why, mem.world_size),
                          file=sys.stderr, flush=True)
                    # a permanent single-rank failure is survivable once
                    # the rank is out of the job
                    kind = "retryable"
                elif kind != "permanent":
                    print("launch.py: NOT evicting slot %d — world size "
                          "%d already at --min-workers %d floor"
                          % (slot, world, args.min_workers),
                          file=sys.stderr, flush=True)
        if kind == "permanent":
            print("launch.py: not restarting — failure is not retryable "
                  "(%d restart attempts preserved)"
                  % (args.max_restarts - attempt),
                  file=sys.stderr, flush=True)
            mem.record(attempt, "gave_up", rc=rc)
            return rc or 1
        # exponential backoff: crash loops (a flaky host, a wedged
        # coordinator port) get geometrically more breathing room
        delay = min(args.restart_backoff * (2 ** attempt),
                    args.restart_backoff_max)
        if delay > 0:
            print("launch.py: backing off %.2fs before restart" % delay,
                  file=sys.stderr, flush=True)
            time.sleep(delay)
        print("launch.py: restarting job from checkpoints "
              "(attempt %d/%d) after worker %d failure: world size "
              "%d -> %d, evicted now %s, sitting out %s"
              % (attempt + 1, args.max_restarts, failed_rank, world,
                 mem.world_size, evicted_now or "none",
                 sorted(mem.evicted_at) or "none"),
              file=sys.stderr, flush=True)
        prev_world = world
    return 1


def _serve_port_doc(run_dir, slot):
    """Read a slot's port file (bootstrap discovery: host/port plus the
    incarnation stamp the worker minted at boot).  Raises OSError /
    ValueError when the worker has not published yet."""
    path = os.path.join(run_dir, "serve-port-slot%d.json" % slot)
    with open(path) as f:
        return json.load(f)


def _serve_rpc(run_dir, slot, msg, timeout=2.0):
    """One length-framed JSON RPC to a serve worker, dependency-free.

    The supervisor must not import the framework to supervise it (a
    jax import in the launcher would cost seconds and a device lock),
    so this is a deliberate stdlib-only mirror of
    ``mxnet_tpu/serving/rpc.py``'s wire format: 4-byte big-endian
    length + UTF-8 JSON, one connection per call.  Returns
    ``(reply_doc, port_doc)``; raises OSError/ValueError on any
    transport or framing trouble — callers treat that as "no answer",
    never as death (confirmation needs an incarnation change or a
    kill-ack, and the supervisor IS the kill-ack authority)."""
    doc = _serve_port_doc(run_dir, slot)
    payload = json.dumps(msg).encode("utf-8")
    with socket.create_connection(
            (doc.get("host", "127.0.0.1"), int(doc["port"])),
            timeout=timeout) as s:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(timeout)
        s.sendall(struct.pack(">I", len(payload)) + payload)
        buf = b""
        while len(buf) < 4:
            chunk = s.recv(4 - len(buf))
            if not chunk:
                raise OSError("serve rpc: connection closed mid-frame")
            buf += chunk
        (n,) = struct.unpack(">I", buf)
        body = b""
        while len(body) < n:
            chunk = s.recv(n - len(body))
            if not chunk:
                raise OSError("serve rpc: connection closed mid-frame")
            body += chunk
    return json.loads(body.decode("utf-8")), doc


def _serve_stop_fleet(args, run_dir, state):
    """Stop the fleet the control-plane way: order each live worker to
    drain over an incarnation-authenticated ``drain`` RPC (the stamp
    comes from the slot's own port file, so a replacement that took
    the slot between discovery and the call refuses the stale order),
    wait for the exit-80s, then escalate SIGTERM→SIGKILL on anything
    that did not answer or did not die — which is exactly the
    ``serve.worker.zombie`` drill: a worker that swallows its drain
    RPC still leaves, it just leaves feet-first."""
    for slot, st in sorted(state.items()):
        if st["proc"] is None or st["down"]:
            continue
        try:
            doc = _serve_port_doc(run_dir, slot)
            inc = {"pid": doc.get("pid"),
                   "attempt": doc.get("attempt"),
                   "nonce": doc.get("nonce")}
            reply, _ = _serve_rpc(run_dir, slot,
                                  {"method": "drain",
                                   "incarnation": inc},
                                  timeout=2.0)
            acked = bool(reply.get("ok"))
        except (OSError, ValueError):
            acked = False
        if not acked:
            print("launch.py: serve slot %d did not ack its drain RPC "
                  "— will escalate with signals" % slot,
                  file=sys.stderr, flush=True)
    procs = [st["proc"] for st in state.values()
             if st["proc"] is not None]
    deadline = time.time() + max(args.kill_grace, 5.0)
    while time.time() < deadline and \
            any(p.poll() is None for p in procs):
        time.sleep(0.1)
    stragglers = [p for p in procs if p.poll() is None]
    if stragglers:
        print("launch.py: %d worker(s) still up after the drain RPCs "
              "— escalating" % len(stragglers),
              file=sys.stderr, flush=True)
        _escalate_kill(stragglers, signal.SIGTERM, args.kill_grace)


def _serve_hb_check(args, run_dir, hb_dir, slot, st, now):
    """Per-slot liveness via the heartbeat RPC (ISSUE 17).

    Before a worker's first successful heartbeat (engine still
    building, port file unpublished) the PR-4 heartbeat FILE covers
    the boot window — the watchdog thread touches it from process
    start, so a worker wedged before it can even serve RPCs is still
    caught.  From first contact on, only the RPC view counts: the
    slot is killed when heartbeats have been silent past
    ``--heartbeat-timeout`` AND the progress sequence (decode steps,
    weights epoch) has not advanced either — a worker that answers
    nothing but is provably decoding is partitioned, not wedged, and
    killing it is the router's fencing problem, not ours."""
    p = st["proc"]
    if st["hb_ok_at"] is None:
        # boot window: heartbeat-file mtime is the only signal
        hb = os.path.join(hb_dir, "hb-%d.json" % slot)
        try:
            age = now - os.stat(hb).st_mtime
        except OSError:
            age = None
        if age is not None and age > args.heartbeat_timeout:
            print("launch.py: serve slot %d heartbeat silent %.1fs "
                  "during boot — killing the wedged replica"
                  % (slot, age), file=sys.stderr, flush=True)
            _escalate_kill([p], signal.SIGTERM, args.kill_grace)
    if now >= st["next_hb_at"]:
        st["next_hb_at"] = now + min(1.0,
                                     args.heartbeat_timeout / 4.0)
        try:
            reply, _doc = _serve_rpc(
                run_dir, slot, {"method": "heartbeat"},
                timeout=min(2.0, args.heartbeat_timeout))
        except (OSError, ValueError):
            reply = None
        if reply is not None and reply.get("ok"):
            st["hb_ok_at"] = now
            prog = reply.get("progress") or {}
            seq = (prog.get("decode_steps"),
                   prog.get("weights_epoch"))
            if seq != st["progress_seq"]:
                st["progress_seq"] = seq
                st["progress_at"] = now
    ok_at = st["hb_ok_at"]
    if ok_at is None:
        return
    hb_gap = now - ok_at
    prog_gap = now - (st["progress_at"] if st["progress_at"]
                      is not None else ok_at)
    if hb_gap > args.heartbeat_timeout and \
            prog_gap > args.heartbeat_timeout:
        print("launch.py: serve slot %d heartbeat RPC silent %.1fs "
              "with no decode progress — killing the wedged replica"
              % (slot, hb_gap), file=sys.stderr, flush=True)
        _escalate_kill([p], signal.SIGTERM, args.kill_grace)


def _serve_telemetry_pull(args, run_dir, slot, st, now):
    """Collector half of the RPC telemetry plane (ISSUE 18): pull the
    slot's newly-drained telemetry over the ``telemetry_pull`` RPC and
    append each returned line to ``<telemetry-dir>/stream-slot<K>.
    jsonl`` — the exact layout the in-worker file emitter writes and
    serve_report/job_report/telemetry_report already read, but
    assembled over the wire (the multi-host seam: the supervisor needs
    no shared filesystem with its workers).  The cursor is
    supervisor-held; a worker replacement declares ``reset`` in-band
    (the line schema carries the new identity), a missed pull just
    resumes at the old cursor next interval, and the per-pull chunk
    loop is bounded so one firehose worker cannot wedge supervision.
    Lines land whole via single O_APPEND writes, so readers can apply
    the usual torn-tail skip-and-count discipline."""
    if now < st["next_tel_at"]:
        return
    st["next_tel_at"] = now + args.telemetry_pull_interval
    path = os.path.join(args.telemetry_dir,
                        "stream-slot%d.jsonl" % slot)
    try:
        for _ in range(8):
            msg = {"method": "telemetry_pull"}
            if st["tel_cursor"] is not None:
                msg["cursor"] = st["tel_cursor"]
            reply, _doc = _serve_rpc(run_dir, slot, msg, timeout=2.0)
            if not reply.get("ok"):
                return
            st["tel_cursor"] = reply.get("cursor")
            line = (json.dumps(reply["line"]) + "\n").encode("utf-8")
            fd = os.open(path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
            if not reply.get("more"):
                return
    except (OSError, ValueError, KeyError):
        pass  # no answer is a missed interval, never a supervision event


def _serve_spawn(args, mem, run_dir, hb_dir, cmd, slot, attempt):
    """One serving-replica worker process for ``slot``: the training
    env contract (slot == rank — serving has no collective world to
    re-pack) plus the serve-plane exports: the slot's PORT FILE (the
    bootstrap-discovery channel carrying the worker's incarnation
    stamp) and the heartbeat dir (boot-window liveness only — once a
    worker answers its first heartbeat RPC, the supervisor watches
    the RPC view, not file mtimes)."""
    env = dict(os.environ)
    env.update(_worker_env(args, mem, mem.world_size, slot, slot,
                           attempt, None))
    env.update({
        "MXTPU_HEARTBEAT_DIR": hb_dir,
        "MXTPU_SERVE_PORT_FILE":
            os.path.join(run_dir, "serve-port-slot%d.json" % slot),
    })
    # orphan reclamation (ISSUE 19): a fleet-wide abandon window for
    # vanished streaming clients; operator-set env wins (ssh-env rule)
    if getattr(args, "serve_abandon_s", 0) and \
            "MXTPU_SERVE_ABANDON_S" not in os.environ:
        env["MXTPU_SERVE_ABANDON_S"] = str(args.serve_abandon_s)
    if args.cpu_fake_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    mem.record(attempt, "spawn", slot=slot)
    return subprocess.Popen(cmd, env=env)


def _serve_loop(args, cmd):
    """The ``--serve`` fleet supervisor: N serving-replica processes,
    each its own slot, supervised INDIVIDUALLY (serving has no
    collective — one replica dying must replace that replica, never
    tear the fleet down, which is the whole point of the
    out-of-process shape).

    Per-slot policy, journaled into ``membership.json`` like the
    elastic trainer:

    - exit 80 (graceful drain): ``drain`` + ``replace`` transitions,
      respawned immediately with no backoff and no blame;
    - retryable exits (SIGKILL, 75, 77, crashes): ``failure`` +
      ``replace``, respawned with per-slot exponential backoff; the
      respawn shares the launch's AOT cache so the replacement comes
      up warm (0 foreground compiles).  A slot blamed
      ``--evict-after`` consecutive times (or any permanent exit) is
      evicted — a crash-looping replica must not burn the budget
      forever;
    - ``--max-restarts`` bounds TOTAL failure-respawns across the
      fleet (drain respawns are planned and free);
    - liveness is the RPC view (ISSUE 17): the supervisor polls each
      worker's ``heartbeat`` RPC and kills (SIGTERM→SIGKILL) a slot
      whose heartbeats go silent past ``--heartbeat-timeout`` with no
      decode-progress advance; heartbeat FILES cover only the boot
      window before the worker publishes its port file.

    The fleet runs until ``<run-dir>/serve-stop`` appears (the
    operator/driver's shutdown handle — each worker is ordered to
    drain over an incarnation-authenticated RPC, exit 80, with
    SIGTERM escalation for non-responders) or every slot is down
    (exit 1)."""
    mem = _Membership(args)
    run_dir = args.run_dir
    hb_dir = os.path.join(run_dir, "hb")
    os.makedirs(hb_dir, exist_ok=True)
    stop_path = os.path.join(run_dir, "serve-stop")
    # a stop handle is a one-shot order to THIS fleet: a stale file
    # from the previous fleet in a reused run dir must not drain the
    # fresh one the moment it spawns
    try:
        os.unlink(stop_path)
    except OSError:
        pass
    state = {}
    for slot in list(mem.active):
        state[slot] = {"attempt": 0, "streak": 0, "down": False,
                       "next_spawn_at": None,
                       "hb_ok_at": None, "progress_seq": None,
                       "progress_at": None, "next_hb_at": 0.0,
                       "tel_cursor": None, "next_tel_at": 0.0,
                       "proc": _serve_spawn(args, mem, run_dir, hb_dir,
                                            cmd, slot, 0)}
    pull_telemetry = bool(args.telemetry_dir) and \
        args.telemetry_pull_interval > 0
    fail_respawns = 0
    try:
        while True:
            if os.path.exists(stop_path):
                print("launch.py: serve-stop requested — draining the "
                      "fleet over the control RPC", file=sys.stderr,
                      flush=True)
                mem.record(0, "stop")
                if pull_telemetry:
                    # last collection before the workers drain away:
                    # short runs must still leave a complete tree
                    now = time.time()
                    for slot, st in sorted(state.items()):
                        if st["proc"] is not None and not st["down"]:
                            st["next_tel_at"] = 0.0
                            _serve_telemetry_pull(args, run_dir, slot,
                                                  st, now)
                _serve_stop_fleet(args, run_dir, state)
                mem.record(0, "complete")
                return 0
            now = time.time()
            if all(st["down"] for st in state.values()):
                if all(st.get("clean") for st in state.values()):
                    mem.record(0, "complete")
                    return 0
                mem.record(0, "gave_up",
                           reason="every serving slot is down")
                print("launch.py: every serving slot is down — giving "
                      "up", file=sys.stderr, flush=True)
                return 1
            for slot, st in sorted(state.items()):
                if st["down"]:
                    continue
                p = st["proc"]
                if p is None:
                    if now >= st["next_spawn_at"]:
                        st["attempt"] += 1
                        # fresh incarnation: the RPC liveness clock
                        # restarts with it
                        st["hb_ok_at"] = None
                        st["progress_seq"] = None
                        st["progress_at"] = None
                        st["next_hb_at"] = 0.0
                        st["proc"] = _serve_spawn(
                            args, mem, run_dir, hb_dir, cmd, slot,
                            st["attempt"])
                    continue
                rc = p.poll()
                if rc is None:
                    if args.heartbeat_timeout > 0:
                        _serve_hb_check(args, run_dir, hb_dir, slot,
                                        st, now)
                    if pull_telemetry:
                        _serve_telemetry_pull(args, run_dir, slot, st,
                                              now)
                    continue
                if rc == 0:
                    # clean completion (e.g. a worker's own run-length
                    # backstop): the slot is done — not blamed, not
                    # respawned
                    mem.record(st["attempt"], "complete", slot=slot)
                    st["down"] = True
                    st["clean"] = True
                    st["proc"] = None
                    continue
                kind, reason = classify_exit(rc)
                if kind == "clean":
                    mem.record(st["attempt"], "drain", slot=slot,
                               rc=rc, reason=reason)
                    st["streak"] = 0
                    st["proc"] = None
                    st["next_spawn_at"] = now  # a drain is planned
                    mem.record(st["attempt"], "replace", slot=slot)
                    print("launch.py: serve slot %d drained "
                          "gracefully; spinning replacement (no "
                          "backoff)" % slot, file=sys.stderr,
                          flush=True)
                    continue
                st["streak"] += 1
                mem.record(st["attempt"], "failure", slot=slot, rc=rc,
                           kind=kind, reason=reason,
                           consecutive_failures=st["streak"])
                print("launch.py: serve slot %d (attempt %d) failed: "
                      "%s (%s)" % (slot, st["attempt"], kind, reason),
                      file=sys.stderr, flush=True)
                if kind == "permanent" or \
                        st["streak"] >= max(1, args.evict_after):
                    why = ("exit classified permanent"
                           if kind == "permanent" else
                           "%d consecutive failures (--evict-after "
                           "%d)" % (st["streak"], args.evict_after))
                    if slot in mem.active:
                        mem.evict(st["attempt"], slot, why)
                    st["down"] = True
                    st["proc"] = None
                    print("launch.py: serve slot %d evicted (%s)"
                          % (slot, why), file=sys.stderr, flush=True)
                    continue
                if fail_respawns >= args.max_restarts:
                    mem.record(st["attempt"], "gave_up", slot=slot,
                               rc=rc,
                               reason="--max-restarts %d exhausted"
                               % args.max_restarts)
                    st["down"] = True
                    st["proc"] = None
                    print("launch.py: serve slot %d down — restart "
                          "budget exhausted" % slot, file=sys.stderr,
                          flush=True)
                    continue
                fail_respawns += 1
                delay = min(args.restart_backoff
                            * (2 ** (st["streak"] - 1)),
                            args.restart_backoff_max)
                st["proc"] = None
                st["next_spawn_at"] = now + delay
                mem.record(st["attempt"], "replace", slot=slot,
                           backoff_s=delay)
                print("launch.py: respawning serve slot %d in %.2fs "
                      "(failure respawn %d/%d)"
                      % (slot, delay, fail_respawns,
                         args.max_restarts),
                      file=sys.stderr, flush=True)
            time.sleep(0.15)
    except KeyboardInterrupt:
        print("launch.py: interrupt — stopping the serve fleet",
              file=sys.stderr, flush=True)
        _escalate_kill([st["proc"] for st in state.values()
                        if st["proc"] is not None],
                       signal.SIGINT, args.kill_grace)
        mem.record(0, "interrupted")
        return 1


def launch_local(args, cmd):
    if args.dry_run:
        port = args.port or _free_port()
        for rank in range(args.num_workers):
            # the real per-worker contract, so a pasted line reproduces
            # what a launched worker actually sees
            env = _worker_env(args, None, args.num_workers, rank, rank,
                              0, None)
            env["MXTPU_COORDINATOR"] = "127.0.0.1:%d" % port
            envs = " ".join("%s=%s" % (k, shlex.quote(v))
                            for k, v in sorted(env.items()))
            print("%s %s" % (envs,
                             " ".join(shlex.quote(c) for c in cmd)))
        return 0
    return _restart_loop(args, _run_local_once, cmd)


def _ssh_commands(args, cmd, attempt=0, mem=None, prev_world=None):
    """→ [ssh argv per worker] — one worker per ACTIVE slot's hostfile
    entry (elastic mode drops an evicted slot's host from the attempt
    and readmits it later; the slot→host binding is stable)."""
    assert args.hostfile, "--launcher ssh requires -H hostfile"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    hosts = (hosts * args.num_workers)[:args.num_workers]
    slots = list(mem.active) if mem is not None \
        else list(range(args.num_workers))
    world = len(slots)
    port = args.port or _free_port()
    coordinator = "%s:%d" % (socket.gethostname(), port)
    if mem is not None:
        mem.record(attempt, "attempt_start", port=port)
    out = []
    for rank, slot in enumerate(slots):
        # _worker_env covers the cache exports too: warm-start caches
        # assume a shared filesystem across hosts (the usual pod setup);
        # a host-local path just cold-starts harmlessly
        env = _worker_env(args, mem, world, rank, slot, attempt,
                          prev_world)
        env["MXTPU_COORDINATOR"] = coordinator
        envs = " ".join("%s=%s" % (k, shlex.quote(v))
                        for k, v in sorted(env.items()))
        remote = "cd %s; %s %s" % (shlex.quote(os.getcwd()), envs,
                                   " ".join(shlex.quote(c) for c in cmd))
        # -tt forces a remote tty so the remote process group dies with
        # the ssh client when the monitor tears the job down — without
        # it one remote worker failing leaves the others running forever
        out.append(["ssh", "-tt", "-o", "StrictHostKeyChecking=no",
                    "-o", "BatchMode=yes", hosts[slot], remote])
    return out


def _run_ssh_once(args, cmd, attempt, mem, prev_world=None):
    """One ssh job attempt, monitored like the local launcher: the first
    remote worker failing (its ssh client exits nonzero) tears the whole
    job down and reports the failed rank, instead of the old
    wait-for-everyone loop that left surviving hosts running forever.
    No heartbeat files here — they are host-local; stall defense on ssh
    jobs is the in-process watchdog (exit 75 propagates through ssh)."""
    procs = [subprocess.Popen(argv)
             for argv in _ssh_commands(args, cmd, attempt, mem,
                                       prev_world)]
    return _monitor_procs(args, procs, label="ssh worker")


def launch_ssh(args, cmd):
    if args.dry_run:
        for argv in _ssh_commands(args, cmd):
            print(" ".join(shlex.quote(a) for a in argv))
        return 0
    return _restart_loop(args, _run_ssh_once, cmd)


def _mpi_command(args, cmd):
    """One mpirun invocation (Open MPI CLI: -x/--hostfile); ranks adopt
    their mpirun-assigned rank at startup (base.py maps
    OMPI_COMM_WORLD_RANK/PMI_RANK/... onto the worker-rank contract the
    same way the reference's dmlc_tracker mpi mode rode mpirun,
    reference tools/launch.py:70).

    The coordinator must live where rank 0 runs: the first hostfile
    host (mpirun fills hosts in order), else this host.  Pass --port
    to pin a port known open on that host; _free_port() only checks
    the launcher."""
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.split()[0] for h in f if h.strip()]
        coord_host = hosts[0]
    else:
        coord_host = socket.gethostname()
    port = args.port or _free_port()
    coordinator = "%s:%d" % (coord_host, port)
    argv = ["mpirun", "-np", str(args.num_workers)]
    if args.hostfile:
        argv += ["--hostfile", args.hostfile]
    argv += ["-x", "MXTPU_COORDINATOR=%s" % coordinator,
             "-x", "MXTPU_NUM_WORKERS=%d" % args.num_workers,
             "-x", "MXTPU_RANK_FROM_MPI=1",
             "-x", "DMLC_ROLE=worker",
             "-x", "DMLC_NUM_WORKER=%d" % args.num_workers]
    for k, v in _cache_env(args).items():
        argv += ["-x", "%s=%s" % (k, v)]
    return argv + list(cmd)


def launch_mpi(args, cmd):
    argv = _mpi_command(args, cmd)
    if args.dry_run:
        print(" ".join(shlex.quote(a) for a in argv))
        return 0
    return subprocess.call(argv)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference tools/launch.py)")
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="ignored — no parameter servers in the "
                        "all-reduce design (kept for CLI compat)")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for ssh launcher")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the launch commands/environment "
                        "without running anything")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi"],
                        help="cluster type")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    parser.add_argument("--cpu-fake-devices", action="store_true",
                        help="force JAX_PLATFORMS=cpu in workers (local "
                        "fake-cluster testing)")
    parser.add_argument("--local-device-count", type=int, default=0,
                        help="virtual devices per worker process "
                        "(xla_force_host_platform_device_count; test "
                        "multi-chip-per-host jobs without hardware)")
    parser.add_argument("--serve", action="store_true",
                        help="serving-fleet mode (local launcher): run "
                        "the command as -n independent serving-replica "
                        "slots (tools/serve_worker.py), each "
                        "supervised INDIVIDUALLY — exit 80 journals "
                        "drain/replace and respawns immediately; "
                        "crashes/SIGKILL/stalls respawn with backoff "
                        "(AOT-warm via the shared --aot-cache-dir), "
                        "evicting a slot after --evict-after "
                        "consecutive failures; every transition lands "
                        "in <run-dir>/membership.json.  Each slot "
                        "publishes <run-dir>/serve-port-slot<K>.json "
                        "for router proxies "
                        "(mxnet_tpu.serving.rpc.fleet_proxies); stop "
                        "the fleet by creating <run-dir>/serve-stop")
    parser.add_argument("--elastic", action="store_true",
                        help="make world size a per-restart decision: a "
                        "worker slot blamed for --evict-after "
                        "consecutive failures (or one permanent exit) "
                        "is dropped from the next attempt — survivors "
                        "re-ranked contiguously, job resumes from "
                        "checkpoints at N-1 — and re-admitted after "
                        "sitting out --readmit-after attempts; "
                        "transitions recorded in <run-dir>/"
                        "membership.json")
    parser.add_argument("--min-workers", type=int, default=1,
                        help="elastic shrink floor: never evict below "
                        "this many workers (default 1)")
    parser.add_argument("--evict-after", type=int, default=2,
                        help="consecutive failures of the same worker "
                        "slot before elastic mode evicts it (default 2; "
                        "a permanent exit evicts immediately from "
                        "attempt 1 on — an attempt-0 permanent failure "
                        "still fails the job fast, since a usage/import "
                        "error hits every rank identically)")
    parser.add_argument("--readmit-after", type=int, default=1,
                        help="attempts an evicted slot sits out before "
                        "being re-admitted (default 1)")
    parser.add_argument("--run-dir", default=None,
                        help="job run dir holding membership.json (the "
                        "elastic transition journal; render with "
                        "tools/perf_probe/telemetry_report.py).  "
                        "Default: a per-launch temp dir when --elastic, "
                        "else none")
    parser.add_argument("--telemetry-dir", default=None,
                        help="job-scope telemetry tree: each worker "
                        "slot's JSON-lines stream (MXTPU_TELEMETRY, "
                        "append-only per slot), crash postmortems and "
                        "stall-stacks (MXTPU_POSTMORTEM_DIR) all land "
                        "here, next to membership.json — the input of "
                        "tools/perf_probe/job_report.py.  Default: "
                        "<run-dir>/telemetry when --run-dir is set "
                        "(incl. the --elastic auto run dir); pass 'off' "
                        "to disable.  Operator-set MXTPU_TELEMETRY / "
                        "MXTPU_POSTMORTEM_DIR env always wins")
    parser.add_argument("--telemetry-interval", type=float, default=10.0,
                        help="seconds between telemetry stream lines "
                        "per worker (the [:interval] half of the "
                        "MXTPU_TELEMETRY spec; default 10)")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="restart the whole job this many times when "
                        "a worker dies (workers resume from their own "
                        "checkpoints; MXTPU_RESTART_ATTEMPT tells them "
                        "which attempt is running); non-retryable "
                        "failures (e.g. exit code 2) stop immediately")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds between restarts; doubles "
                        "each attempt (exponential backoff)")
    parser.add_argument("--restart-backoff-max", type=float, default=60.0,
                        help="backoff ceiling in seconds")
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="kill + restart the job when a worker's "
                        "heartbeat file (touched by mxnet_tpu.watchdog "
                        "under MXTPU_HEARTBEAT_DIR) goes quiet for this "
                        "many seconds (0 = off); catches workers wedged "
                        "in native code that their in-process watchdog "
                        "cannot see")
    parser.add_argument("--kill-grace", type=float, default=5.0,
                        help="seconds to wait between teardown "
                        "escalation steps (SIGINT/SIGTERM → SIGKILL)")
    parser.add_argument("--telemetry-pull-interval", type=float,
                        default=2.0,
                        help="--serve only: seconds between "
                        "telemetry_pull RPC collections per slot "
                        "(appended to <telemetry-dir>/stream-slot<K>"
                        ".jsonl — fleet observability with no shared "
                        "filesystem reads; 0 disables the collector)")
    parser.add_argument("--serve-abandon-s", type=float, default=0.0,
                        help="--serve only: reclaim a streamed request "
                        "whose client stopped polling for this many "
                        "seconds (typed verdict 'abandoned', slot + KV "
                        "pages released — SERVING.md §10; exported to "
                        "workers as MXTPU_SERVE_ABANDON_S; 0 = off; "
                        "operator-set env wins)")
    parser.add_argument("--aot-cache-dir", default=None,
                        help="compiled-executable warm-start cache "
                        "exported to workers as MXTPU_AOT_CACHE_DIR (+ "
                        "JAX_COMPILATION_CACHE_DIR fallback); persists "
                        "across restart attempts so a restarted rank "
                        "deserializes the fused step instead of "
                        "recompiling it.  Default: a per-job temp dir; "
                        "pass 'off' to disable")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command for launching the program")
    args = parser.parse_args(argv)
    cmd = [c for c in args.command if c != "--"]
    assert cmd, "no command given"
    if args.serve and args.launcher != "local":
        print("launch.py: --serve is a local-launcher mode",
              file=sys.stderr, flush=True)
        return 2
    if args.serve and not args.run_dir:
        # the run dir is the fleet's rendezvous (port files, heartbeat
        # tree, membership journal, serve-stop handle) — it must exist
        args.run_dir = tempfile.mkdtemp(prefix="mxtpu-serve-")
    if args.elastic and args.launcher == "mpi":
        print("launch.py: --elastic is a local/ssh launcher feature "
              "(mpirun owns process placement; use your MPI runtime's "
              "fault tolerance there) — ignoring it", file=sys.stderr,
              flush=True)
        args.elastic = False
    if args.elastic and not args.run_dir:
        # the membership journal is the record of what the job looked
        # like over time — keep it after exit (unlike the heartbeat
        # dirs), and say where it lives
        args.run_dir = tempfile.mkdtemp(prefix="mxtpu-run-")
    if args.run_dir and args.launcher != "mpi":
        # (mpi bypasses _restart_loop/_Membership: no journal to announce)
        os.makedirs(args.run_dir, exist_ok=True)
        print("launch.py: membership journal at %s"
              % os.path.join(args.run_dir, "membership.json"),
              file=sys.stderr, flush=True)
    if args.telemetry_dir == "off":
        args.telemetry_dir = None
    elif not args.telemetry_dir and args.run_dir and \
            args.launcher != "mpi":
        args.telemetry_dir = os.path.join(args.run_dir, "telemetry")
    if args.telemetry_dir and args.launcher != "mpi":
        os.makedirs(args.telemetry_dir, exist_ok=True)
        print("launch.py: job telemetry tree at %s (render with "
              "tools/perf_probe/job_report.py)" % args.telemetry_dir,
              file=sys.stderr, flush=True)
    elif args.telemetry_dir:
        # mpi has no slot contract to key the per-worker streams by
        print("launch.py: --telemetry-dir is a local/ssh launcher "
              "feature — ignoring it under mpi", file=sys.stderr,
              flush=True)
        args.telemetry_dir = None
    auto_cache_dir = None
    if args.aot_cache_dir == "off":
        args.aot_cache_dir = None
    elif not args.aot_cache_dir:
        # one dir per launch INVOCATION, shared by every restart attempt
        # — the whole point is that attempt N+1 finds attempt N's
        # compiled executables (operator env wins when already set)
        args.aot_cache_dir = os.environ.get("MXTPU_AOT_CACHE_DIR")
        if not args.aot_cache_dir:
            args.aot_cache_dir = auto_cache_dir = \
                tempfile.mkdtemp(prefix="mxtpu-aot-")
    try:
        if args.serve:
            if args.dry_run:
                # the real per-slot contract, so a pasted line
                # reproduces what a launched replica actually sees
                # (mem=None like launch_local's dry run: a DRY run
                # must not journal a 'launch' transition into a run
                # dir a live fleet may be using)
                for slot in range(args.num_workers):
                    env = _worker_env(args, None, args.num_workers,
                                      slot, slot, 0, None)
                    env.update({
                        "MXTPU_HEARTBEAT_DIR":
                            os.path.join(args.run_dir, "hb"),
                        "MXTPU_SERVE_PORT_FILE": os.path.join(
                            args.run_dir,
                            "serve-port-slot%d.json" % slot),
                    })
                    envs = " ".join(
                        "%s=%s" % (k, shlex.quote(v))
                        for k, v in sorted(env.items()))
                    print("%s %s" % (envs, " ".join(
                        shlex.quote(c) for c in cmd)))
                return 0
            return _serve_loop(args, cmd)
        if args.launcher == "local":
            return launch_local(args, cmd)
        if args.launcher == "mpi":
            return launch_mpi(args, cmd)
        return launch_ssh(args, cmd)
    finally:
        # the auto-created cache only serves restart attempts of THIS
        # invocation; leaving serialized executables + a min-compile-
        # time-0 XLA cache in /tmp per launch would leak without bound.
        # Operator-provided dirs (flag or env) are theirs to keep.
        if auto_cache_dir:
            shutil.rmtree(auto_cache_dir, ignore_errors=True)
            if args.launcher in ("ssh", "mpi") and args.hostfile:
                # without a shared filesystem every remote host grew its
                # own copy at the exported path; rm it there too (the
                # path is launcher-generated, never operator data; mpi
                # hostfile hosts are reachable over ssh in every mpirun
                # deployment this launcher targets)
                _cleanup_remote_cache(args, auto_cache_dir)


def _cleanup_remote_cache(args, path):
    """Best-effort rm of the auto-created cache dir on each ssh host."""
    try:
        with open(args.hostfile) as f:
            # first token only: mpi hostfiles carry "host slots=N"
            hosts = sorted({h.split()[0] for h in f if h.strip()})
    except OSError:
        return
    for host in hosts:
        subprocess.call(
            ["ssh", "-o", "StrictHostKeyChecking=no", "-o",
             "BatchMode=yes", host, "rm -rf %s" % shlex.quote(path)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


if __name__ == "__main__":
    sys.exit(main())
