#!/usr/bin/env python
"""Launch a distributed training job.

Port of /root/reference/tools/launch.py, re-targeted: the reference
spawned ps-lite scheduler/server/worker processes through dmlc_tracker
(ssh/mpi/sge/yarn, launch.py:59-84); the TPU-native framework has no
server processes — every worker is a JAX process in one collective mesh.

Launchers:
- ``local``: spawn N worker processes on this host wired together with
  ``jax.distributed`` (coordinator on 127.0.0.1).  Each worker sees the
  env contract DMLC_ROLE=worker, DMLC_NUM_WORKER, DMLC_WORKER_ID (kept
  for script compat) plus JAX_* coordination vars.  This is the
  reference's `--launcher local` fake-cluster test mode
  (tests/nightly/dist_sync_kvstore.py workflow).
- ``ssh``: run one worker per host from `-H hostfile` via ssh, pointing
  all of them at this host's coordinator port; monitored like local
  (first failure tears the job down, --max-restarts applies).

Failure handling: worker exits are classified retryable/permanent
(classify_exit) with exponential backoff between restarts; hangs are
caught by the per-rank heartbeat monitor (--heartbeat-timeout, files
touched by mxnet_tpu.watchdog under MXTPU_HEARTBEAT_DIR) and by the
in-process watchdog's stall exit code 75 — see ROBUSTNESS.md §5/§7.
Restarts warm-start: every attempt shares one AOT executable cache
(--aot-cache-dir → MXTPU_AOT_CACHE_DIR + jax's persistent compile
cache), so a restarted rank deserializes the compiled fit step instead
of paying trace+compile again — see PERF.md §12.
- On real TPU pods, prefer the platform launcher (GKE/queued resources):
  every pod VM already runs one process; pass --use-env-ranks to adopt
  the platform-provided rank env instead of spawning.
"""
from __future__ import annotations

import argparse
import os
import shlex
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

# exit-code contract with mxnet_tpu/watchdog.py (kept literal here: the
# launcher must work without the package importable on this host)
STALL_EXIT = 75         # EX_TEMPFAIL: watchdog stall — retryable
PORT_IN_USE_EXIT = 76   # coordinator port bind failure — retryable


def _cache_env(args):
    """Warm-start env for workers: the AOT executable cache
    (mxnet_tpu.aot_cache — restarted ranks deserialize the compiled fit
    step instead of re-tracing + re-compiling it) plus jax's own
    persistent compilation cache as the fallback layer for every other
    program.  The dir is created once per launch invocation and reused
    across restart attempts — that persistence IS the feature.  Values
    already exported by the operator are never overridden."""
    if not getattr(args, "aot_cache_dir", None):
        return {}
    # Always export the resolved dir: main() already made the operator's
    # choice (explicit flag > their env > auto temp dir), and ssh workers
    # see ONLY this env string — the launcher's environment does not ride
    # along, so "already exported locally" must not suppress the export.
    # Operator-set jax cache knobs are forwarded verbatim for the same
    # reason; the min-compile-time default of 0 exists because jax's own
    # threshold (1s) would skip most of a small model's programs, and a
    # restart wants them all.
    return {
        "MXTPU_AOT_CACHE_DIR": args.aot_cache_dir,
        "JAX_COMPILATION_CACHE_DIR":
            os.environ.get("JAX_COMPILATION_CACHE_DIR") or
            os.path.join(args.aot_cache_dir, "xla"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS":
            os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                           "0"),
    }


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _escalate_kill(procs, first_sig=signal.SIGTERM, grace=5.0):
    """Tear a job down with bounded patience: ``first_sig`` → wait up to
    ``grace`` → SIGTERM → ``grace`` → SIGKILL, then reap.  Every stop
    path (worker death, heartbeat stall, Ctrl-C) routes through here, so
    a worker that ignores polite signals — or is the very wedged process
    we are killing *because* it stopped responding — can delay teardown
    by at most 2×grace, never forever."""
    seq = []
    for sig in (first_sig, signal.SIGTERM, signal.SIGKILL):
        if not seq or seq[-1] != sig:
            seq.append(sig)
    for sig in seq:
        alive = [p for p in procs if p.poll() is None]
        if not alive:
            break
        for p in alive:
            try:
                p.send_signal(sig)
            except OSError:
                pass  # exited between poll and signal
        if sig == signal.SIGKILL:
            break
        deadline = time.time() + grace
        while time.time() < deadline and \
                any(p.poll() is None for p in procs):
            time.sleep(0.05)
    # bounded reap: even SIGKILL cannot collect a process stuck in
    # uninterruptible sleep (D-state — the hung-NFS case this defense
    # targets); waiting forever here would convert a detected worker
    # hang into an undetected launcher hang
    deadline = time.time() + max(grace, 5.0)
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            print("launch.py: giving up reaping pid %d (uninterruptible "
                  "sleep?); continuing teardown" % p.pid,
                  file=sys.stderr, flush=True)
        except Exception:
            pass


def _monitor_procs(args, procs, heartbeat_dir=None, label="worker"):
    """Watch a running job; returns ``(failed_rank, rc)`` — (None, 0) on
    clean completion, rank+code on the first failure (the job is torn
    down first), (-1, 1) on Ctrl-C.

    Two failure channels (the collective-era replacement for ps-lite's
    server heartbeat/recovery hooks, reference src/kvstore/
    kvstore_dist.h:59-62):

    - **exit**: a worker dying strands its peers inside a collective, so
      the launcher — not the survivors — detects the death and kills the
      job.
    - **heartbeat silence** (``--heartbeat-timeout`` > 0): a worker that
      *hangs* — wedged in native code under the GIL, swapped out, so
      even its in-process watchdog can't run — stops touching its
      per-rank heartbeat file (written by mxnet_tpu.watchdog inside the
      worker).  A stale mtime past the deadline is treated as a stall:
      the job is killed and the rank reported with the stall exit code
      (75), which classify_exit maps to retryable.  Workers that never
      wrote a heartbeat (non-mxnet commands) are not monitored.
    """
    try:
        while True:
            running = False
            for rank, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running = True
                elif rc != 0:
                    # one worker died — peers may be stranded in a
                    # collective; kill the job (politely first: peers
                    # flush telemetry postmortems on SIGTERM)
                    print("launch.py: %s %d exited with %d; "
                          "terminating remaining workers"
                          % (label, rank, rc), file=sys.stderr,
                          flush=True)
                    _escalate_kill(procs, signal.SIGTERM,
                                   args.kill_grace)
                    return rank, rc
            if not running:
                return None, 0
            if heartbeat_dir and args.heartbeat_timeout > 0:
                now = time.time()
                for rank, p in enumerate(procs):
                    if p.poll() is not None:
                        continue
                    hb = os.path.join(heartbeat_dir,
                                      "hb-%d.json" % rank)
                    try:
                        age = now - os.stat(hb).st_mtime
                    except OSError:
                        continue  # never wrote one: not monitored
                    if age > args.heartbeat_timeout:
                        print("launch.py: %s %d heartbeat silent for "
                              "%.1fs (deadline %.1fs) — declaring the "
                              "rank stalled and terminating the job"
                              % (label, rank, age,
                                 args.heartbeat_timeout),
                              file=sys.stderr, flush=True)
                        _escalate_kill(procs, signal.SIGTERM,
                                       args.kill_grace)
                        return rank, STALL_EXIT
            time.sleep(0.2)
    except KeyboardInterrupt:
        # bounded Ctrl-C teardown: SIGINT first (KeyboardInterrupt in
        # the worker → its finally blocks / atexit postmortems run),
        # then the escalation ladder — never an unbounded wait() on a
        # worker that swallows the signal
        print("launch.py: interrupt — stopping workers (SIGINT, then "
              "escalating after %.1fs grace)" % args.kill_grace,
              file=sys.stderr, flush=True)
        _escalate_kill(procs, signal.SIGINT, args.kill_grace)
        return -1, 1


def _run_local_once(args, cmd, attempt):
    """One local job attempt: spawn N workers wired to a fresh
    coordinator port (``--port 0`` re-picks per attempt, so a port left
    wedged by the previous attempt is simply abandoned) plus a fresh
    heartbeat run dir, then monitor to completion or teardown."""
    port = args.port or _free_port()
    coordinator = "127.0.0.1:%d" % port
    hb_dir = tempfile.mkdtemp(prefix="mxtpu-hb-")
    procs = []
    try:
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env.update({
                # JAX multi-process coordination
                "MXTPU_COORDINATOR": coordinator,
                "MXTPU_NUM_WORKERS": str(args.num_workers),
                "MXTPU_WORKER_RANK": str(rank),
                "MXTPU_RESTART_ATTEMPT": str(attempt),
                # per-rank heartbeat files — exported even when
                # --heartbeat-timeout is 0: the files are the "where
                # was it" record on any kill, and the worker watchdog's
                # stall diagnostics fall back to this dir when
                # MXTPU_POSTMORTEM_DIR is unset (cost: one small write
                # per worker per second)
                "MXTPU_HEARTBEAT_DIR": hb_dir,
                # reference env contract (dmlc_tracker) for script compat
                "DMLC_ROLE": "worker",
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_NUM_SERVER": "0",
                "DMLC_WORKER_ID": str(rank),
            })
            env.update(_cache_env(args))
            if args.cpu_fake_devices:
                env["JAX_PLATFORMS"] = "cpu"
                env.pop("PALLAS_AXON_POOL_IPS", None)
            if args.local_device_count:
                flags = env.get("XLA_FLAGS", "")
                env["XLA_FLAGS"] = (
                    "%s --xla_force_host_platform_device_count"
                    "=%d" % (flags, args.local_device_count)).strip()
            procs.append(subprocess.Popen(cmd, env=env))
        return _monitor_procs(args, procs, heartbeat_dir=hb_dir)
    finally:
        # a stalled worker without MXTPU_POSTMORTEM_DIR falls back to
        # dumping its stack trace / postmortem HERE — deleting those
        # would erase the diagnosis the stall exit just promised
        try:
            diagnostics = [n for n in os.listdir(hb_dir)
                           if n.startswith(("stall-stacks-",
                                            "postmortem-"))]
        except OSError:
            diagnostics = []
        if diagnostics:
            print("launch.py: stall diagnostics preserved in %s (%s)"
                  % (hb_dir, ", ".join(sorted(diagnostics))),
                  file=sys.stderr, flush=True)
        else:
            shutil.rmtree(hb_dir, ignore_errors=True)


def classify_exit(rc):
    """Classify a failed worker's exit code → ('retryable'|'permanent',
    reason).

    Restart attempts are a scarce budget; burning one on a failure that
    will repeat identically (CLI misuse exit 2, unresolvable/unrunnable
    command 126/127) just delays the terminal error.  Deaths by signal
    (rc < 0: OOM-killer SIGKILL, preemption SIGTERM, segfaults) and
    generic runtime failures (rc == 1: an uncaught exception
    mid-training) are exactly what checkpoint-restart exists for.  Note
    the interpreter exits 1 for uncaught ImportError too — exit codes
    cannot distinguish an import-time crash from a mid-training one, so
    those retry conservatively (bounded by the backoff schedule).

    Two dedicated retryable classes from the hang-defense layer
    (mxnet_tpu/watchdog.py): 75 (EX_TEMPFAIL) is a diagnosed stall —
    the worker's watchdog dumped stacks + postmortem and self-terminated,
    or this launcher declared heartbeat silence; 76 is a coordinator
    port bind failure — a restart with ``--port 0`` picks a fresh port."""
    if rc < 0:
        return "retryable", "killed by signal %d" % (-rc)
    if rc == STALL_EXIT:
        return "retryable", ("exit code 75: stall (watchdog/heartbeat "
                             "detected a hang; stacks + postmortem "
                             "dumped)")
    if rc == PORT_IN_USE_EXIT:
        return "retryable", ("exit code 76: coordinator port in use — "
                             "restart re-picks the port (--port 0)")
    if rc == 2:
        return "permanent", ("exit code 2: usage/import-time error — "
                             "would fail identically on every attempt")
    if rc in (126, 127):
        return "permanent", "exit code %d: command not runnable" % rc
    return "retryable", "exit code %d: runtime failure" % rc


def _restart_loop(args, run_once, cmd):
    """The classify → backoff → restart-from-checkpoints policy, shared
    by the local and ssh launchers."""
    for attempt in range(args.max_restarts + 1):
        failed_rank, rc = run_once(args, cmd, attempt)
        if failed_rank is None:
            return 0
        if failed_rank == -1 or attempt == args.max_restarts:
            return rc or 1
        kind, reason = classify_exit(rc)
        print("launch.py: worker %d failure classified %s (%s)"
              % (failed_rank, kind, reason), file=sys.stderr, flush=True)
        if kind == "permanent":
            print("launch.py: not restarting — failure is not retryable "
                  "(%d restart attempts preserved)"
                  % (args.max_restarts - attempt),
                  file=sys.stderr, flush=True)
            return rc or 1
        # exponential backoff: crash loops (a flaky host, a wedged
        # coordinator port) get geometrically more breathing room
        delay = min(args.restart_backoff * (2 ** attempt),
                    args.restart_backoff_max)
        if delay > 0:
            print("launch.py: backing off %.2fs before restart" % delay,
                  file=sys.stderr, flush=True)
            time.sleep(delay)
        print("launch.py: restarting job from checkpoints "
              "(attempt %d/%d) after worker %d failure"
              % (attempt + 1, args.max_restarts, failed_rank),
              file=sys.stderr, flush=True)
    return 1


def launch_local(args, cmd):
    if args.dry_run:
        port = args.port or _free_port()
        for rank in range(args.num_workers):
            envs = ("MXTPU_COORDINATOR=127.0.0.1:%d MXTPU_NUM_WORKERS=%d "
                    "MXTPU_WORKER_RANK=%d DMLC_ROLE=worker "
                    "DMLC_NUM_WORKER=%d DMLC_WORKER_ID=%d"
                    % (port, args.num_workers, rank, args.num_workers,
                       rank))
            print("%s %s" % (envs,
                             " ".join(shlex.quote(c) for c in cmd)))
        return 0
    return _restart_loop(args, _run_local_once, cmd)


def _ssh_commands(args, cmd, attempt=0):
    """→ [ssh argv per worker] — one worker per hostfile entry."""
    assert args.hostfile, "--launcher ssh requires -H hostfile"
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    hosts = (hosts * args.num_workers)[:args.num_workers]
    port = args.port or _free_port()
    coordinator = "%s:%d" % (socket.gethostname(), port)
    out = []
    # warm-start caches assume a shared filesystem across hosts (the
    # usual pod setup); a host-local path just cold-starts harmlessly
    cache_envs = "".join(" %s=%s" % (k, shlex.quote(v))
                         for k, v in sorted(_cache_env(args).items()))
    for rank, host in enumerate(hosts):
        envs = ("MXTPU_COORDINATOR=%s MXTPU_NUM_WORKERS=%d "
                "MXTPU_WORKER_RANK=%d MXTPU_RESTART_ATTEMPT=%d "
                "DMLC_ROLE=worker DMLC_NUM_WORKER=%d "
                "DMLC_WORKER_ID=%d%s"
                % (shlex.quote(coordinator), args.num_workers, rank,
                   attempt, args.num_workers, rank, cache_envs))
        remote = "cd %s; %s %s" % (shlex.quote(os.getcwd()), envs,
                                   " ".join(shlex.quote(c) for c in cmd))
        # -tt forces a remote tty so the remote process group dies with
        # the ssh client when the monitor tears the job down — without
        # it one remote worker failing leaves the others running forever
        out.append(["ssh", "-tt", "-o", "StrictHostKeyChecking=no",
                    "-o", "BatchMode=yes", host, remote])
    return out


def _run_ssh_once(args, cmd, attempt):
    """One ssh job attempt, monitored like the local launcher: the first
    remote worker failing (its ssh client exits nonzero) tears the whole
    job down and reports the failed rank, instead of the old
    wait-for-everyone loop that left surviving hosts running forever.
    No heartbeat files here — they are host-local; stall defense on ssh
    jobs is the in-process watchdog (exit 75 propagates through ssh)."""
    procs = [subprocess.Popen(argv)
             for argv in _ssh_commands(args, cmd, attempt)]
    return _monitor_procs(args, procs, label="ssh worker")


def launch_ssh(args, cmd):
    if args.dry_run:
        for argv in _ssh_commands(args, cmd):
            print(" ".join(shlex.quote(a) for a in argv))
        return 0
    return _restart_loop(args, _run_ssh_once, cmd)


def _mpi_command(args, cmd):
    """One mpirun invocation (Open MPI CLI: -x/--hostfile); ranks adopt
    their mpirun-assigned rank at startup (base.py maps
    OMPI_COMM_WORLD_RANK/PMI_RANK/... onto the worker-rank contract the
    same way the reference's dmlc_tracker mpi mode rode mpirun,
    reference tools/launch.py:70).

    The coordinator must live where rank 0 runs: the first hostfile
    host (mpirun fills hosts in order), else this host.  Pass --port
    to pin a port known open on that host; _free_port() only checks
    the launcher."""
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [h.split()[0] for h in f if h.strip()]
        coord_host = hosts[0]
    else:
        coord_host = socket.gethostname()
    port = args.port or _free_port()
    coordinator = "%s:%d" % (coord_host, port)
    argv = ["mpirun", "-np", str(args.num_workers)]
    if args.hostfile:
        argv += ["--hostfile", args.hostfile]
    argv += ["-x", "MXTPU_COORDINATOR=%s" % coordinator,
             "-x", "MXTPU_NUM_WORKERS=%d" % args.num_workers,
             "-x", "MXTPU_RANK_FROM_MPI=1",
             "-x", "DMLC_ROLE=worker",
             "-x", "DMLC_NUM_WORKER=%d" % args.num_workers]
    for k, v in _cache_env(args).items():
        argv += ["-x", "%s=%s" % (k, v)]
    return argv + list(cmd)


def launch_mpi(args, cmd):
    argv = _mpi_command(args, cmd)
    if args.dry_run:
        print(" ".join(shlex.quote(a) for a in argv))
        return 0
    return subprocess.call(argv)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference tools/launch.py)")
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="ignored — no parameter servers in the "
                        "all-reduce design (kept for CLI compat)")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for ssh launcher")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the launch commands/environment "
                        "without running anything")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi"],
                        help="cluster type")
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    parser.add_argument("--cpu-fake-devices", action="store_true",
                        help="force JAX_PLATFORMS=cpu in workers (local "
                        "fake-cluster testing)")
    parser.add_argument("--local-device-count", type=int, default=0,
                        help="virtual devices per worker process "
                        "(xla_force_host_platform_device_count; test "
                        "multi-chip-per-host jobs without hardware)")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="restart the whole job this many times when "
                        "a worker dies (workers resume from their own "
                        "checkpoints; MXTPU_RESTART_ATTEMPT tells them "
                        "which attempt is running); non-retryable "
                        "failures (e.g. exit code 2) stop immediately")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds between restarts; doubles "
                        "each attempt (exponential backoff)")
    parser.add_argument("--restart-backoff-max", type=float, default=60.0,
                        help="backoff ceiling in seconds")
    parser.add_argument("--heartbeat-timeout", type=float, default=0.0,
                        help="kill + restart the job when a worker's "
                        "heartbeat file (touched by mxnet_tpu.watchdog "
                        "under MXTPU_HEARTBEAT_DIR) goes quiet for this "
                        "many seconds (0 = off); catches workers wedged "
                        "in native code that their in-process watchdog "
                        "cannot see")
    parser.add_argument("--kill-grace", type=float, default=5.0,
                        help="seconds to wait between teardown "
                        "escalation steps (SIGINT/SIGTERM → SIGKILL)")
    parser.add_argument("--aot-cache-dir", default=None,
                        help="compiled-executable warm-start cache "
                        "exported to workers as MXTPU_AOT_CACHE_DIR (+ "
                        "JAX_COMPILATION_CACHE_DIR fallback); persists "
                        "across restart attempts so a restarted rank "
                        "deserializes the fused step instead of "
                        "recompiling it.  Default: a per-job temp dir; "
                        "pass 'off' to disable")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command for launching the program")
    args = parser.parse_args(argv)
    cmd = [c for c in args.command if c != "--"]
    assert cmd, "no command given"
    auto_cache_dir = None
    if args.aot_cache_dir == "off":
        args.aot_cache_dir = None
    elif not args.aot_cache_dir:
        # one dir per launch INVOCATION, shared by every restart attempt
        # — the whole point is that attempt N+1 finds attempt N's
        # compiled executables (operator env wins when already set)
        args.aot_cache_dir = os.environ.get("MXTPU_AOT_CACHE_DIR")
        if not args.aot_cache_dir:
            args.aot_cache_dir = auto_cache_dir = \
                tempfile.mkdtemp(prefix="mxtpu-aot-")
    try:
        if args.launcher == "local":
            return launch_local(args, cmd)
        if args.launcher == "mpi":
            return launch_mpi(args, cmd)
        return launch_ssh(args, cmd)
    finally:
        # the auto-created cache only serves restart attempts of THIS
        # invocation; leaving serialized executables + a min-compile-
        # time-0 XLA cache in /tmp per launch would leak without bound.
        # Operator-provided dirs (flag or env) are theirs to keep.
        if auto_cache_dir:
            shutil.rmtree(auto_cache_dir, ignore_errors=True)
            if args.launcher in ("ssh", "mpi") and args.hostfile:
                # without a shared filesystem every remote host grew its
                # own copy at the exported path; rm it there too (the
                # path is launcher-generated, never operator data; mpi
                # hostfile hosts are reachable over ssh in every mpirun
                # deployment this launcher targets)
                _cleanup_remote_cache(args, auto_cache_dir)


def _cleanup_remote_cache(args, path):
    """Best-effort rm of the auto-created cache dir on each ssh host."""
    try:
        with open(args.hostfile) as f:
            # first token only: mpi hostfiles carry "host slots=N"
            hosts = sorted({h.split()[0] for h in f if h.strip()})
    except OSError:
        return
    for host in hosts:
        subprocess.call(
            ["ssh", "-o", "StrictHostKeyChecking=no", "-o",
             "BatchMode=yes", host, "rm -rf %s" % shlex.quote(path)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


if __name__ == "__main__":
    sys.exit(main())
