#!/usr/bin/env python
"""Live fleet matrix over the RPC telemetry plane (ISSUE 18).

``serve_report`` answers fleet questions post-hoc from the run-dir
tree; THIS tool asks a *running* fleet directly — one ``heartbeat`` +
one ``telemetry_pull`` per replica per refresh, no shared filesystem,
no run-dir reads beyond bootstrap port-file discovery.  Per replica it
renders what an operator triaging "slot 2 is suspected" needs in one
row (SERVING.md §9):

- engine state: occupancy / decode slots, queue depth, free KV pages,
  shed + drain + SLO state, installed weights epoch, decode steps;
- efficiency: prefix-cache hit rate, speculative acceptance rate, and
  goodput tok/s (counter deltas between refreshes — the first
  snapshot shows cumulative totals);
- delivery (ISSUE 19): live streams (``strm``), waiting pollers
  (``wait``), and reclaimed-orphan count (``orph``) from the engine
  snapshot's stream block — a rising ``orph`` says clients are
  vanishing mid-stream (the ``orphan_reclaim`` alert fires on the
  same counter);
- liveness: heartbeat round-trip + incarnation stamp, and — when run
  inside the router process via :func:`collect_matrix` — the local
  suspicion / breaker / fence gauges the proxies maintain (a
  standalone fleet_top has no proxy state and prints ``-``);
- the newest ``alert`` events the replica's rules fired, straight off
  the pulled stream.

Modes: ``--once`` prints one matrix and exits (``--json`` emits the
raw rows — the drill/cron contract, asserted by ``BENCH_MODE=serve``);
default is a watch loop every ``--interval`` seconds.  Cursors are
held client-side, so watching costs each worker only its newly-drained
events per refresh and never steals from the supervisor's collector.

Usage:

    python tools/perf_probe/fleet_top.py --run-dir /run/fleet --once
    python tools/perf_probe/fleet_top.py --addr 10.0.0.2:7001 \
        --addr 10.0.0.3:7001 --interval 2
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from mxnet_tpu import telemetry as _telemetry           # noqa: E402
from mxnet_tpu.serving import rpc as _rpc               # noqa: E402

#: how many of a replica's newest alert events ride each row
ALERT_TAIL = 4


def discover_targets(run_dir):
    """``[(name, addr), ...]`` from a ``launch.py --serve`` run dir's
    port files (bootstrap discovery only — everything after this rides
    the RPC plane)."""
    out = []
    for path in sorted(glob.glob(
            os.path.join(run_dir, "serve-port-slot*.json"))):
        m = re.search(r"slot(\d+)\.json$", path)
        name = "slot%s" % (m.group(1) if m else "?")
        try:
            doc = _rpc.read_port_file(path)
            out.append((name,
                        (doc.get("host", "127.0.0.1"),
                         int(doc["port"]))))
        except (OSError, ValueError, KeyError, TypeError):
            out.append((name, None))  # not up yet: rendered as down
    return out


def _rate(num, den):
    return (num / den) if den else None


def _local_liveness(name):
    """Suspicion / breaker / fence state for ``name`` from THIS
    process's registry — meaningful only where the router's proxies
    live.  ``None`` fields mean 'no local evidence', rendered ``-``."""
    suspect = _telemetry.gauge("rpc.suspect.%s" % name).value
    breaker = _telemetry.gauge("rpc.breaker.%s" % name).value
    breaker_s = {0: "closed", 1: "half-open", 2: "open"}.get(breaker)
    confirms = {}
    for n, v in (_telemetry.report().get("counters") or {}).items():
        if n.startswith("rpc.confirmations.") and v:
            confirms[n.rpartition(".")[2]] = v
    return {"suspect": suspect,
            "breaker": breaker_s,
            "confirmations": confirms or None,
            "fenced_results":
                _telemetry.counter("rpc.fenced_results").value or None}


def collect_row(name, addr, cursor=None, timeout_s=2.0,
                local_liveness=True):
    """One fleet-matrix row: pull + heartbeat one replica.  Returns the
    row dict (``up=False`` rows carry only the error) and the advanced
    pull cursor."""
    if addr is None:
        return {"replica": name, "up": False,
                "error": "no port published"}, cursor
    row = {"replica": name, "up": True,
           "addr": "%s:%s" % (addr[0], addr[1])}
    t0 = time.perf_counter()
    try:
        hb = _rpc.rpc_call(addr, {"method": "heartbeat"}, timeout_s,
                           retries=0)
        row["hb_rtt_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        row["incarnation"] = hb.get("incarnation")
        row["draining"] = hb.get("draining")
        prog = hb.get("progress") or {}
        row["decode_steps"] = prog.get("decode_steps")
        row["weights_epoch"] = prog.get("weights_epoch")
    except (_rpc.RpcError, OSError) as e:
        row["up"] = False
        row["error"] = "heartbeat: %s" % e
        return row, cursor
    try:
        reply = _rpc.pull_telemetry(addr, cursor=cursor,
                                    timeout_s=timeout_s)
    except (_rpc.RpcError, OSError) as e:
        row["error"] = "telemetry_pull: %s" % e
        return row, cursor
    cursor = reply["cursor"]
    row["cursor_reset"] = bool(reply.get("reset"))
    line = reply.get("line") or {}
    ctr = line.get("counters") or {}
    row["counters"] = ctr
    row["time_unix"] = line.get("time_unix")
    for snap in line.get("serving") or []:
        row["engine"] = {
            "occupancy": snap.get("occupancy"),
            "num_slots": snap.get("num_slots"),
            "queued": snap.get("queued"),
            "free_pages": snap.get("free_pages"),
            "num_pages": snap.get("num_pages"),
            "shedding": snap.get("shedding"),
            "draining": snap.get("draining"),
            "decode_steps": snap.get("decode_steps"),
            "weights_epoch": snap.get("weights_epoch"),
            "slo": snap.get("slo"),
            "stream": snap.get("stream"),
            "kv_dtype": snap.get("kv_dtype"),
            "kv_bytes_per_token": snap.get("kv_bytes_per_token"),
        }
        break  # one engine per worker process in the fleet layout
    row["prefix_hit_rate"] = _rate(
        ctr.get("serving.prefix.hits", 0),
        ctr.get("serving.prefix.hits", 0)
        + ctr.get("serving.prefix.miss", 0))
    row["spec_accept_rate"] = _rate(
        ctr.get("serving.spec.accepted", 0),
        ctr.get("serving.spec.draft_tokens", 0))
    row["tokens"] = ctr.get("serving.tokens", 0)
    row["goodput_tokens"] = ctr.get("serving.goodput", 0)
    row["alerts"] = [e.get("args") or {}
                     for e in line.get("req_events") or []
                     if e.get("event") == "alert"][-ALERT_TAIL:]
    if local_liveness:
        row["liveness"] = _local_liveness(name)
    return row, cursor


def collect_matrix(targets, cursors=None, prev=None, timeout_s=2.0,
                   local_liveness=True):
    """Rows for every ``(name, addr)`` target; ``cursors`` (mutated in
    place when given) holds per-name pull cursors across refreshes, and
    ``prev`` (the previous call's result) turns cumulative token
    counters into tok/s rates.  This is the in-process entry point the
    partition drill and the router host use — the CLI below is a thin
    loop over it."""
    cursors = {} if cursors is None else cursors
    prev_rows = {r["replica"]: r for r in (prev or {}).get("rows", [])}
    rows = []
    for name, addr in targets:
        row, cursors[name] = collect_row(
            name, addr, cursor=cursors.get(name), timeout_s=timeout_s,
            local_liveness=local_liveness)
        p = prev_rows.get(name)
        if p and row.get("up") and p.get("up") and \
                row.get("time_unix") and p.get("time_unix"):
            dt = row["time_unix"] - p["time_unix"]
            if dt > 0:
                row["tok_s"] = round(
                    (row["tokens"] - p.get("tokens", 0)) / dt, 2)
                row["goodput_tok_s"] = round(
                    (row["goodput_tokens"]
                     - p.get("goodput_tokens", 0)) / dt, 2)
        rows.append(row)
    return {"t": time.time(), "rows": rows}


# -- rendering ---------------------------------------------------------------

def _fmt(v, pct=False):
    if v is None:
        return "-"
    if pct:
        return "%d%%" % round(v * 100)
    return str(v)


def render_matrix(matrix, out=sys.stdout):
    cols = ("replica", "state", "occ", "queue", "free_pg", "kv",
            "prefix", "spec", "tok/s", "strm", "wait", "orph", "hb_ms",
            "susp", "breaker", "epoch")
    rows = []
    for r in matrix["rows"]:
        if not r.get("up"):
            rows.append((r["replica"], "DOWN", "-", "-", "-", "-", "-",
                         "-", "-", "-", "-", "-", "-", "-", "-",
                         r.get("error", "")[:24]))
            continue
        eng = r.get("engine") or {}
        state = "shed" if eng.get("shedding") else (
            "drain" if (eng.get("draining") or r.get("draining"))
            else "ok")
        if r.get("cursor_reset"):
            state += "*"   # cursor discontinuity declared this refresh
        live = r.get("liveness") or {}
        occ = "-"
        if eng.get("num_slots"):
            occ = "%s/%s" % (eng.get("occupancy"), eng.get("num_slots"))
        strm = eng.get("stream") or {}
        rows.append((
            r["replica"], state, occ, _fmt(eng.get("queued")),
            _fmt(eng.get("free_pages")),
            _fmt(eng.get("kv_dtype")),
            _fmt(r.get("prefix_hit_rate"), pct=True),
            _fmt(r.get("spec_accept_rate"), pct=True),
            _fmt(r.get("tok_s", r.get("tokens"))),
            _fmt(strm.get("live")), _fmt(strm.get("waiting")),
            _fmt(strm.get("abandoned")),
            _fmt(r.get("hb_rtt_ms")),
            {1: "SUSPECT", 0: "-"}.get(live.get("suspect"), "-"),
            live.get("breaker") or "-",
            _fmt(r.get("weights_epoch"))))
    widths = [max(len(str(c)),
                  max((len(str(row[i])) for row in rows), default=0))
              for i, c in enumerate(cols)]
    line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
    out.write(line + "\n" + "-" * len(line) + "\n")
    for row in rows:
        out.write("  ".join(str(v).ljust(w)
                            for v, w in zip(row, widths)) + "\n")
    alerts = [(r["replica"], a) for r in matrix["rows"]
              for a in r.get("alerts") or []]
    if alerts:
        out.write("alerts:\n")
        for name, a in alerts:
            out.write("  [%s] %s %s (%s=%s)\n"
                      % (a.get("severity", "?"), name,
                         a.get("rule", "?"), a.get("metric", "?"),
                         a.get("value", "-")))
    out.flush()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir_pos", nargs="?", default=None,
                    metavar="RUN_DIR",
                    help="launch.py --serve run dir (same as "
                         "--run-dir)")
    ap.add_argument("--run-dir", default=None,
                    help="launch.py --serve run dir (port-file "
                         "discovery)")
    ap.add_argument("--addr", action="append", default=[],
                    help="host:port of a worker (repeatable; "
                         "bypasses --run-dir discovery)")
    ap.add_argument("--once", action="store_true",
                    help="one refresh, then exit")
    ap.add_argument("--json", action="store_true",
                    help="emit raw row dicts instead of the table")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="watch-mode refresh seconds")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-RPC deadline seconds")
    args = ap.parse_args(argv)
    run_dir = args.run_dir or args.run_dir_pos
    targets = []
    for a in args.addr:
        host, _, port = a.rpartition(":")
        targets.append((a, (host or "127.0.0.1", int(port))))
    if run_dir:
        targets.extend(discover_targets(run_dir))
    if not targets:
        ap.error("no targets: pass --run-dir and/or --addr")
    cursors, prev = {}, None
    while True:
        matrix = collect_matrix(targets, cursors=cursors, prev=prev,
                                timeout_s=args.timeout)
        if args.json:
            json.dump(matrix, sys.stdout, default=str)
            sys.stdout.write("\n")
            sys.stdout.flush()
        else:
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear, home
            render_matrix(matrix)
        if args.once:
            return 0
        prev = matrix
        time.sleep(max(0.1, args.interval))


if __name__ == "__main__":
    sys.exit(main())
