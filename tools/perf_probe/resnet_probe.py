"""Raw-JAX ResNet-50 train-step ceiling probe: NCHW vs NHWC on one chip."""
import functools, time, sys
import jax, jax.numpy as jnp
from jax import lax
import numpy as np

LAYOUT = sys.argv[1] if len(sys.argv) > 1 else "NHWC"
B = int(sys.argv[2]) if len(sys.argv) > 2 else 256
nhwc = LAYOUT == "NHWC"
dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
caxis = -1 if nhwc else 1

rng = np.random.RandomState(0)
params = []

def conv_w(k, ci, co):
    w = rng.randn(*( (k, k, ci, co) if nhwc else (co, ci, k, k) )).astype(np.float32) * 0.05
    params.append(w)
    return len(params) - 1

def bn_w(c):
    params.append(np.ones((c,), np.float32))
    params.append(np.zeros((c,), np.float32))
    return len(params) - 2

# resnet50 v1: stem + [3,4,6,3] bottleneck stages
stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]
arch = {"stem_conv": conv_w(7, 3, 64), "stem_bn": bn_w(64)}
blocks = []
cin = 64
for n, mid, cout, stride in stages:
    for i in range(n):
        s = stride if i == 0 else 1
        blk = {
            "c1": conv_w(1, cin, mid), "b1": bn_w(mid),
            "c2": conv_w(3, mid, mid), "b2": bn_w(mid),
            "c3": conv_w(1, mid, cout), "b3": bn_w(cout),
            "stride": s,
        }
        if cin != cout or s != 1:
            blk["down"] = conv_w(1, cin, cout)
            blk["down_bn"] = bn_w(cout)
        blocks.append(blk)
        cin = cout
fc_w = rng.randn(2048, 1000).astype(np.float32) * 0.01
params.append(fc_w)
FC = len(params) - 1

def conv(x, w, stride=1, k=1):
    p = k // 2
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(p, p), (p, p)],
        dimension_numbers=lax.conv_dimension_numbers(x.shape, w.shape, dn))

def bn(x, g, b):
    axes = tuple(i for i in range(4) if i != (3 if nhwc else 1))
    m = x.mean(axes, keepdims=True)
    v = ((x - m) ** 2).mean(axes, keepdims=True)
    sh = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
    return (x - m) * lax.rsqrt(v + 1e-5) * g.reshape(sh) + b.reshape(sh)

def fwd(p, x):
    x = conv(x, p[arch["stem_conv"]], 2, 7)
    x = jax.nn.relu(bn(x, p[arch["stem_bn"]], p[arch["stem_bn"] + 1]))
    wdims = (1, 1) if nhwc else (2, 3)
    x = lax.reduce_window(x, -jnp.inf, lax.max,
                          tuple(3 if i in wdims else 1 for i in range(4)),
                          tuple(2 if i in wdims else 1 for i in range(4)),
                          [(0, 0) if i not in wdims else (1, 1) for i in range(4)])
    for blk in blocks:
        idn = x
        y = jax.nn.relu(bn(conv(x, p[blk["c1"]]), p[blk["b1"]], p[blk["b1"] + 1]))
        y = jax.nn.relu(bn(conv(y, p[blk["c2"]], blk["stride"], 3), p[blk["b2"]], p[blk["b2"] + 1]))
        y = bn(conv(y, p[blk["c3"]]), p[blk["b3"]], p[blk["b3"] + 1])
        if "down" in blk:
            idn = bn(conv(x, p[blk["down"]], blk["stride"]), p[blk["down_bn"]], p[blk["down_bn"] + 1])
        x = jax.nn.relu(y + idn)
    x = x.mean((1, 2) if nhwc else (2, 3))
    return x @ p[FC]

def loss_fn(p, x, y):
    pb = [q.astype(jnp.bfloat16) for q in p]
    logits = fwd(pb, x.astype(jnp.bfloat16)).astype(jnp.float32)
    lp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(lp, y[:, None], 1).mean()

@functools.partial(jax.jit, donate_argnums=(0, 1))
def step(p, mom, x, y):
    l, g = jax.value_and_grad(loss_fn)(p, x, y)
    mom = [0.9 * m - 0.05 * gg for m, gg in zip(mom, g)]
    p = [w + m for w, m in zip(p, mom)]
    return p, mom, l

ps = [jnp.asarray(w) for w in params]
mom = [jnp.zeros_like(w) for w in ps]
key = jax.random.PRNGKey(0)
shape = (B, 224, 224, 3) if nhwc else (B, 3, 224, 224)
x = jax.random.normal(key, shape, jnp.float32)
y = jax.random.randint(key, (B,), 0, 1000)

for _ in range(3):
    ps, mom, l = step(ps, mom, x, y)
import numpy as _np
_ = _np.asarray(l)  # force warmup chain
t0 = time.perf_counter()
N = 20
for _ in range(N):
    ps, mom, l = step(ps, mom, x, y)
_ = _np.asarray(l)  # scalar fetch forces the chain (tunnel block_until_ready lies)
dt = time.perf_counter() - t0
imgs = B * N / dt
print("%s bs%d: %.1f img/s  (%.1f ms/step, loss %.3f)"
      % (LAYOUT, B, imgs, dt / N * 1e3, float(l)))
