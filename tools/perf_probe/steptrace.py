"""Per-step dispatch/compile trace for the train hot path (PERF.md,
"Fused train step").  Runs the same MLP fit loop through the fused
Module.fit_step (one donated XLA program per batch) and the split
forward_backward()+update() pair (one program + one update kernel per
parameter), printing profiler.step_stats() for each so dispatch-count
regressions are visible at a glance.

Usage: JAX_PLATFORMS=cpu python tools/perf_probe/steptrace.py
Prints one JSON object: {"fused": {...}, "fused_async_ckpt": {...},
"unfused": {...}} where each side carries steady-state
dispatches_per_step, compile_count and step_time_ema_ms — the
fused_async_ckpt trace runs a per-epoch MXTPU_ASYNC_CKPT=1 checkpoint
inside the loop and asserts the save path adds zero dispatches.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_module(batch=64, dim=32, classes=4, hidden=64, depth=2,
                 n_batches=8, ctx=None, optimizer="sgd",
                 opt_params=(("learning_rate", 0.05), ("momentum", 0.9))):
    """The probe family's MLP fit-loop fixture (restart_probe reuses it
    with bigger sizes): ``depth-1`` hidden relu layers + a softmax
    head.  ``ctx`` may be a device list — the BENCH_MODE=spmd probe
    passes the whole 8-device host mesh."""
    import numpy as np
    import mxnet_tpu as mx

    rs = np.random.RandomState(0)
    X = rs.randn(n_batches * batch, dim).astype(np.float32)
    y = rs.randint(0, classes, size=n_batches * batch).astype(np.float32)
    train = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False,
                              label_name="softmax_label")
    net = mx.sym.Variable("data")
    for i in range(1, depth):
        net = mx.sym.FullyConnected(net, num_hidden=hidden,
                                    name="fc%d" % i)
        net = mx.sym.Activation(net, act_type="relu")
    out = mx.sym.FullyConnected(net, num_hidden=classes,
                                name="fc%d" % depth)
    s = mx.sym.SoftmaxOutput(out, name="softmax")
    mod = mx.mod.Module(s, context=mx.cpu() if ctx is None else ctx)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(kvstore=None, optimizer=optimizer,
                       optimizer_params=opt_params)
    return mod, train


def trace(step_fn, batches, epochs=3):
    """Warm one epoch, then measure steady state (profiler counters AND
    the always-on telemetry phase histograms, reset together)."""
    from mxnet_tpu import profiler, telemetry
    for b in batches:
        step_fn(b)
    # the probe VERIFIES telemetry/step_stats consistency, so recording
    # must be on even under MXTPU_TELEMETRY_OFF=1 in the environment
    telemetry.set_enabled(True)
    profiler.reset_step_stats()
    telemetry.reset()
    t0 = time.perf_counter()
    n = 0
    for _ in range(epochs):
        for b in batches:
            step_fn(b)
            n += 1
    dt = time.perf_counter() - t0
    stats = profiler.step_stats()
    rep = telemetry.report()
    ema = stats["step_time_ema_s"]
    return {
        "steps": n,
        "dispatches_per_step": stats["dispatch_count"] / n,
        "compile_count": stats["compile_count"],
        "skipped_steps": stats["skipped_steps"],
        "step_time_ema_ms": round(ema * 1e3, 3) if ema else None,
        "wall_ms_per_step": round(dt / n * 1e3, 3),
        "phase_counts": {name: p["count"]
                         for name, p in rep["phases"].items()},
        "flight_len": rep["flight"]["len"],
        "flight_maxlen": rep["flight"]["maxlen"],
    }


def run():
    import shutil
    import tempfile

    mod, train = build_module()
    batches = list(train)

    fused = trace(mod.fit_step, batches)

    mod2, _ = build_module()

    def split_step(b):
        from mxnet_tpu import profiler
        mod2.forward_backward(b)
        mod2.update()
        profiler.note_step()  # the fused path notes its own steps

    unfused = trace(split_step, batches)
    n_params = len(mod._param_names)

    # fused loop WITH async checkpointing live: a save per epoch, the
    # write overlapping the following steps.  The snapshot (host fetch +
    # owned copies) and enqueue must add ZERO compiled-program
    # dispatches — the 1.0 dispatch/step contract is asserted on this
    # trace exactly like the plain fused one (bench.py BENCH_MODE=
    # steptrace hard-fails otherwise).
    from mxnet_tpu import checkpoint as _ckpt
    mod3, _ = build_module()
    ckdir = tempfile.mkdtemp(prefix="steptrace-ckpt-")
    prev = os.environ.get("MXTPU_ASYNC_CKPT")
    os.environ["MXTPU_ASYNC_CKPT"] = "1"
    seen = [0]

    def fused_ckpt_step(b):
        mod3.fit_step(b)
        seen[0] += 1
        if seen[0] % len(batches) == 0:  # one checkpoint per epoch
            mod3.save_checkpoint(os.path.join(ckdir, "ck"),
                                 seen[0] // len(batches),
                                 save_optimizer_states=True)

    try:
        fused_async = trace(fused_ckpt_step, batches)
        _ckpt.flush_async()
    finally:
        if prev is None:
            os.environ.pop("MXTPU_ASYNC_CKPT", None)
        else:
            os.environ["MXTPU_ASYNC_CKPT"] = prev
        shutil.rmtree(ckdir, ignore_errors=True)
    # the dispatch-rate contract itself (1.0/step, async saves in-loop)
    # is asserted by bench.py BENCH_MODE=steptrace, same as the plain
    # fused contract — one home per check

    # the telemetry layer must agree with the profiler's step counters:
    # every fused dispatch produced exactly one fit_step.dispatch /
    # fit_step.sync phase record and one flight-recorder entry (the 1.0
    # dispatch/step contract, cross-checked against the new per-phase
    # counters; bench.py BENCH_MODE=steptrace still hard-asserts the
    # dispatch rate itself)
    n = fused["steps"]
    for phase in ("fit_step.dispatch", "fit_step.sync"):
        got = fused["phase_counts"].get(phase, 0)
        assert got == n, (
            "telemetry phase %r recorded %d entries for %d fused steps — "
            "per-phase counters diverged from profiler.step_stats()"
            % (phase, got, n))
    assert fused["flight_len"] == min(n, fused["flight_maxlen"]), (
        "flight recorder held %d records for %d fused steps (ring cap %d)"
        % (fused["flight_len"], n, fused["flight_maxlen"]))

    return {"fused": fused, "fused_async_ckpt": fused_async,
            "unfused": unfused, "n_params": n_params}


def run_spmd(n_dev=8):
    """BENCH_MODE=spmd body: the ZeRO-1 fused step on an n_dev host
    mesh.  Returns per-step dispatch stats plus the sharded-state
    economics (opt-state bytes per device vs total, the estimated
    per-step collective bytes, fallback count) so bench.py can assert
    the 1.0 dispatch/step and 1/N-state contracts."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    if jax.device_count() < n_dev:
        raise RuntimeError(
            "BENCH_MODE=spmd needs %d devices (run under "
            "--xla_force_host_platform_device_count=%d or on real "
            "chips); have %d" % (n_dev, n_dev, jax.device_count()))
    prev = os.environ.get("MXTPU_ZERO")
    os.environ["MXTPU_ZERO"] = "1"
    try:
        ctx = [mx.cpu(i) for i in range(n_dev)]
        # adam: two state leaves per param — the sharpest 1/N contrast
        mod, train = build_module(ctx=ctx, optimizer="adam",
                                  opt_params=(("learning_rate", 0.01),))
        batches = list(train)
        spmd = trace(mod.fit_step, batches)

        fused = mod._fused
        assert fused["zero"] is not None, \
            "MXTPU_ZERO=1 on a mesh bind must engage ZeRO-1"
        # trace() resets telemetry after warmup, wiping the setup-time
        # sharding + cost-attribution gauges — republish both for the
        # report below
        mod._exec._note_sharding_telemetry(
            tuple(fused["update_names"]), fused["state"], fused["zero"])
        mod._exec.publish_cost_telemetry()

        def per_device_bytes(leaf):
            shards = {s.data.shape for s in leaf.addressable_shards}
            return int(np.prod(next(iter(shards)))) * leaf.dtype.itemsize

        total = 0
        per_device = 0
        sharded_leaves = 0
        leaves = 0
        for name, sub in fused["state"].items():
            for leaf in jax.tree_util.tree_leaves(sub):
                leaves += 1
                nb = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
                total += nb
                per_device += per_device_bytes(leaf)
                if not leaf.sharding.is_fully_replicated:
                    sharded_leaves += 1
        # every OTHER fused-step input, per device, from the live
        # arrays' actual shard shapes: params/data/label/aux.  Together
        # with the 1/N state this is what the compiled program's
        # xla.memory.argument_bytes must agree with (±20%,
        # BENCH_MODE=spmd) — the measured cross-check of the ZeRO-1
        # state economics (scalars/rng are a few tens of bytes, inside
        # the tolerance).
        expected_args = per_device
        exe = mod._exec
        for d in (exe.arg_dict, exe.aux_dict):
            for name, arr in d.items():
                expected_args += per_device_bytes(arr._data)
        rep = telemetry.report()
        spmd.update({
            "n_devices": n_dev,
            "opt_state_total_bytes": total,
            "opt_state_bytes_per_device": per_device,
            "opt_state_leaves": leaves,
            "opt_state_leaves_sharded": sharded_leaves,
            "expected_argument_bytes_per_device": expected_args,
            "gauge_opt_state_bytes_per_device":
                rep["gauges"].get("sharding.opt_state_bytes_per_device"),
            "gauge_collective_bytes_per_step":
                rep["gauges"].get("sharding.collective_bytes_per_step"),
            "gauge_collective_bytes_modeled":
                rep["gauges"].get("sharding.collective_bytes_modeled"),
            "gauge_xla_memory_argument_bytes":
                rep["gauges"].get("xla.memory.argument_bytes"),
            "gauge_xla_cost_flops":
                rep["gauges"].get("xla.cost.flops_per_step"),
            "collective_ops":
                (mod._exec._cost_doc or {}).get("collectives", {})
                .get("ops"),
            "sharding_fallbacks":
                rep["counters"].get("sharding.fallbacks", 0),
        })
        return spmd
    finally:
        if prev is None:
            os.environ.pop("MXTPU_ZERO", None)
        else:
            os.environ["MXTPU_ZERO"] = prev


if __name__ == "__main__":
    if os.environ.get("STEPTRACE_SPMD") == "1":
        print(json.dumps(run_spmd()))
    else:
        print(json.dumps(run()))
