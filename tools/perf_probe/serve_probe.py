"""Serving-path probe: continuous batching vs the sequential predictor.

Synthetic OPEN-LOOP load generator (Poisson arrivals — the generator
never waits for the server, so queueing delay is measured, not hidden)
over mixed prompt/output lengths, driven through two servers built on
the SAME model with the SAME greedy workload:

- **continuous** — ``mxnet_tpu.serving.ServingEngine``: fixed decode
  slots, paged KV cache, ONE donated XLA program per decode step for
  all resident sequences (the tentpole path);
- **sequential** — the predictor discipline the serving stack replaces:
  one request at a time, each new token a full fixed-shape forward over
  the padded context (``Predictor.forward``'s compiled-program contract
  — no KV cache, no cross-request batching), tokens via the same greedy
  argmax.

Reported per side: tokens/s, TTFT and TPOT p50/p99, queue wait, mean
batch occupancy.  Hard contracts asserted by ``BENCH_MODE=serve``
(bench.py):

- exactly ONE decode dispatch per token step (all resident sequences
  advance in it) and one dispatch per admitted request's prefill —
  nothing else dispatches in the serving loop;
- ZERO steady-state recompiles across request churn (slots joining /
  leaving never change a program shape);
- both sides emit IDENTICAL tokens (greedy determinism: the paged
  engine is bit-equivalent to the dense forward);
- warm replica spin-up (``measure_spinup``, restart_probe pattern: two
  subprocesses sharing one AOT cache dir) reaches its first token with
  ZERO foreground serving-program compiles;
- **degraded mode** (``run_degraded``, ISSUE 11): the same workload
  through a 2-replica Router with one replica killed mid-probe
  (``serve.replica.lost``) — zero dropped accepted requests, tokens
  bit-identical to the unfaulted run, and the replacement replica
  spawns AOT-warm (0 foreground compiles).  Per-verdict accounting is
  pinned too: 0 ``failed``, and exactly the killed replica's in-flight
  count ``retried`` — the degraded contract covers verdicts, not just
  totals;
- **request-scope observability** (ISSUE 13): the degraded drill runs
  against a REAL artifact tree (telemetry stream + router journal in
  the run-dir layout) and ``serve_report.py`` must reconstruct every
  accepted request's lifecycle with exactly one terminal verdict, link
  each failed-over request across both replicas by trace id, name the
  killed replica in the blame section, emit a merged chrome trace that
  loads as one file, and reconcile traced tokens with the
  ``serving.tokens``/``serving.goodput`` counters bit-exactly;
  ``measure_trace_overhead`` microbenches the per-decode-step tracing
  cost in isolation (``MXTPU_SERVE_TRACE_BUDGET_US``, default 2);
- **fleet drill** (``run_fleet``, ISSUE 14): the same contracts across
  REAL process boundaries — serve_worker subprocesses behind the RPC
  plane, one replica armed ``rpc.drop`` (circuit breaker trips, then
  recovers via the half-open probe once the replica heals) and one
  armed ``serve.replica.sigkill`` (real SIGKILL mid-probe → confirmed
  death → journaled failover → a REPLACEMENT PROCESS spun on the
  shared AOT cache with 0 foreground compiles) — 0 dropped, tokens
  bit-identical to the unfaulted run, all hard-asserted;
- **partition drill** (``run_partition``, ISSUE 17): the same fleet
  with NO shared run dir (per-worker private tmp dirs, addr-pinned
  proxies, one bootstrap port-file read) — heartbeat-only loss raises
  suspicion but ZERO failovers; a real partition confirms
  ``fence_expiry``, fails over, and FENCES the zombie's late
  completions (0 double-delivered, bit-identical tokens,
  ``rpc.fenced_results`` >= 1), all hard-asserted;
- **telemetry plane** (ISSUE 18): the partition drill's router host
  assembles per-replica telemetry ONLY via the ``telemetry_pull`` RPC
  (the workers' private dirs hold no readable stream) and
  ``serve_report`` over that pull-only tree must be green — lawful
  lifecycles, bit-exact traced-vs-counter token accounting, >= 1
  default alert rule fired and rendered — while
  ``fleet_top.collect_matrix`` returns a complete live matrix;
  ``measure_collector_impact`` pulls after EVERY engine step and the
  hot-path contracts (1.0 decode dispatch/step, 0 steady-state
  recompiles) must survive, with the steady-state pull itself under
  ``MXTPU_TELEMETRY_PULL_BUDGET`` µs (default 2000);
- **streamed delivery** (``run_streaming``, ISSUE 19): a poll-per-step
  client plane over an open-loop trace — cursor-assembled
  streams bit-identical to the engine's token lists (exactly-once),
  1.0 decode dispatch/step and 0 recompiles WITH polling, streamed
  TTFT p50 < 0.5x the unary completion p50 on a decode-dominated
  trace (under a saturating burst queue wait dominates both classes
  equally — the ratio would measure the scheduler); a cancel drill (typed
  ``cancelled`` verdicts mid-decode AND queued, pages restored), the
  ``serve.client.vanish`` drill (silent pollers reclaimed
  ``abandoned``, conservation green, ``orphan_reclaim`` alert fired),
  and a kill-mid-stream fleet drill — a REAL SIGKILL injected only
  once the victim's streams have delivered tokens, the client cursor
  resuming over the survivor's bit-identical re-decode with no gap
  and no dup, plus ``serve.stream.drop`` re-poll recovery;
- **capacity multipliers** (``run_prefix`` / ``run_gqa``, ISSUE 15):
  a system-prompt-heavy Poisson mix with per-request sampling on half
  the requests, cache-on vs cache-off on the SAME workload — prefix
  hit-rate > 0, >= 30% fewer prefill tokens, tokens bit-identical,
  1.0 decode dispatch/step and 0 steady-state recompiles with cache +
  sampling enabled; and grouped-query attention at ``K_kv = H/2`` —
  kernel-vs-oracle equivalence at mixed ragged lengths plus >= 1.5x
  resident sequences in the same page-pool bytes.

Usage: JAX_PLATFORMS=cpu python tools/perf_probe/serve_probe.py
Prints one JSON object.  ``--no-fleet`` / ``--no-spinup`` skip the
subprocess-heavy sections.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from restart_probe import _pct  # noqa: E402 — shared percentile helper


def build_net(vocab=256, n_layer=2, d_model=128, n_head=4, max_len=64,
              seed=0):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import gpt

    np.random.seed(seed)
    mx.random.seed(seed)
    net = gpt.GPTLM(vocab, n_layer, d_model, n_head, max_len=max_len)
    net.initialize()
    return net


def make_workload(n_requests=24, mean_interarrival_s=0.004,
                  prompt_lens=(4, 24), new_tokens=(8, 24), vocab=256,
                  seed=7):
    """[(arrival_offset_s, prompt int32[L], max_new)] — Poisson process
    (exponential inter-arrival), uniform mixed lengths.  Seeded: both
    servers replay the identical trace."""
    import numpy as np
    rng = np.random.RandomState(seed)
    t = 0.0
    out = []
    for _ in range(n_requests):
        t += float(rng.exponential(mean_interarrival_s))
        lo, hi = prompt_lens
        plen = int(rng.randint(lo, hi + 1))
        nlo, nhi = new_tokens
        out.append((t, rng.randint(0, vocab, plen).astype(np.int32),
                    int(rng.randint(nlo, nhi + 1))))
    return out


def _req_stats(ttfts, tpots, waits):
    ttfts, tpots, waits = sorted(ttfts), sorted(tpots), sorted(waits)
    return {
        "ttft_p50_ms": round(_pct(ttfts, 0.5) * 1e3, 3),
        "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
        "tpot_p50_ms": (round(_pct(tpots, 0.5) * 1e3, 3)
                        if tpots else None),
        "tpot_p99_ms": (round(_pct(tpots, 0.99) * 1e3, 3)
                        if tpots else None),
        "queue_wait_p50_ms": (round(_pct(waits, 0.5) * 1e3, 3)
                              if waits else None),
        "queue_wait_p99_ms": (round(_pct(waits, 0.99) * 1e3, 3)
                              if waits else None),
    }


def run_continuous(net, workload, num_slots=8, page_size=16,
                   max_prefill_len=32, max_seq_len=48, num_pages=None,
                   prefix_cache=None, sampling=None, spec_k=None,
                   kv_dtype=None):
    """Open-loop drive of the ServingEngine; returns throughput, latency
    percentiles, occupancy, and the dispatch/compile accounting —
    WITH request-scope tracing live (it is always on: the 1.0
    dispatch/step and recompile contracts below therefore hold with the
    tracing plane enabled, and goodput must equal raw tokens on this
    unfaulted run).

    ``prefix_cache``: forwarded to the engine (None = its default);
    ``sampling``: optional per-request SamplingParams list aligned with
    the workload (None entries = greedy); ``spec_k``: speculative
    decode depth (None = the engine's env default, 0 = off)."""
    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.serving import ServingEngine
    import numpy as np

    eng = ServingEngine(net, num_slots=num_slots, page_size=page_size,
                        max_prefill_len=max_prefill_len,
                        max_seq_len=max_seq_len, num_pages=num_pages,
                        prefix_cache=prefix_cache, spec_k=spec_k,
                        kv_dtype=kv_dtype)
    # warmup: both programs execute once (first-call overhead, twin
    # hot-swap settle) before the timed workload
    eng.generate([np.zeros(4, np.int32)], max_new=2)
    profiler.reset_step_stats()
    telemetry.reset()   # clean counter/trace baseline for the deltas
    base = profiler.step_stats()
    d0, c0 = base["dispatch_count"], base["compile_count"]
    steps0, prefills0 = eng.decode_steps, eng.prefills
    slot_steps0, discarded0 = eng.spec_slot_steps, eng.spec_discarded

    reqs = []
    pending = list(workload)
    samp = list(sampling) if sampling is not None else [None] * len(
        pending)
    t_start = time.perf_counter()
    while pending or not eng.sched.idle:
        now = time.perf_counter() - t_start
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.pop(0)
            reqs.append(eng.submit(prompt, max_new,
                                   sampling=samp[len(reqs)]))
        if eng.step() == 0 and pending:
            # idle gap before the next arrival: wait it out off-device
            time.sleep(min(1e-4, max(0.0, pending[0][0] - now)))
    wall = time.perf_counter() - t_start

    stats = profiler.step_stats()
    decode_steps = eng.decode_steps - steps0
    prefills = eng.prefills - prefills0
    dispatches = stats["dispatch_count"] - d0
    total_tokens = sum(len(r.tokens) for r in reqs)
    decode_tokens = total_tokens - prefills  # 1 token/request from prefill
    # request-scope accounting on the unfaulted run: traced token
    # events and goodput must BOTH equal the raw token counter
    traced = telemetry.count_token_events(telemetry.request_events())
    out = {
        "tokens_counter": telemetry.counter("serving.tokens").value,
        "goodput_counter": telemetry.counter("serving.goodput").value,
        "traced_tokens": traced,
        "requests": len(reqs),
        "num_slots": num_slots,
        "total_tokens": total_tokens,
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(total_tokens / wall, 2),
        "decode_steps": decode_steps,
        "prefill_dispatches": prefills,
        "total_dispatches": dispatches,
        # the tentpole contract: every decode step is ONE program for
        # ALL residents; the only other dispatches are one per prefill
        "decode_dispatches_per_step": round(
            (dispatches - prefills) / max(1, decode_steps), 4),
        "steady_state_compiles": stats["compile_count"] - c0,
        "mean_batch_occupancy": round(
            decode_tokens / max(1, decode_steps), 3),
        "tokens": [list(map(int, r.tokens)) for r in reqs],
        # prefix-cache accounting (counters were reset above, so these
        # are this run's deltas; all 0 with the cache off)
        "prefill_tokens":
            telemetry.counter("serving.prefill_tokens").value,
        "prefix_hits": telemetry.counter("serving.prefix.hits").value,
        "prefix_miss": telemetry.counter("serving.prefix.miss").value,
        "prefix_shared_pages":
            telemetry.counter("serving.prefix.shared_pages").value,
        "prefix_cow_copies":
            telemetry.counter("serving.prefix.cow_copies").value,
        "sampling_requests":
            telemetry.counter("serving.sampling.requests").value,
        # speculative-decode accounting (ISSUE 16; all 0 with spec off).
        # tokens_per_slot_step is the per-sequence multiplier — decode
        # tokens per slot participation — exactly 1.0 for a
        # non-speculative engine by construction
        "spec_k": eng.spec_k,
        "spec_draft_tokens":
            telemetry.counter("serving.spec.draft_tokens").value,
        "spec_accepted": telemetry.counter("serving.spec.accepted").value,
        "spec_rejected": telemetry.counter("serving.spec.rejected").value,
        "spec_rollbacks":
            telemetry.counter("serving.spec.rollbacks").value,
        "spec_slot_steps": eng.spec_slot_steps - slot_steps0,
        "spec_discarded": eng.spec_discarded - discarded0,
        "tokens_per_slot_step": round(
            decode_tokens / (eng.spec_slot_steps - slot_steps0), 4)
        if eng.spec_slot_steps > slot_steps0 else 1.0,
    }
    out.update(_req_stats([r.ttft_s for r in reqs],
                          [r.tpot_s for r in reqs
                           if r.tpot_s is not None],
                          [r.queue_wait_s for r in reqs]))
    return out


def run_sequential(net, workload, t_pad=48):
    """The baseline the ISSUE names: sequential per-request
    ``Predictor.forward`` — one fixed-shape compiled full forward per
    generated token, requests strictly one at a time in arrival order.
    Causal attention makes right-padding invisible to position
    ``len-1``, so greedy tokens match the cached engine bit-for-bit."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from mxnet_tpu.gluon.block import functionalize

    fn, params = functionalize(net, jnp.zeros((1, t_pad), jnp.int32))

    @jax.jit
    def fwd_next(params, toks, length):
        (logits,), _ = fn(params, toks)
        row = lax.dynamic_index_in_dim(logits[0], length - 1, 0,
                                       keepdims=False)
        return row.argmax(-1).astype(jnp.int32)

    # warmup compile outside the timed region (parity with continuous)
    np.asarray(fwd_next(params, jnp.zeros((1, t_pad), jnp.int32),
                        jnp.int32(1)))

    ttfts, tpots, waits, all_tokens = [], [], [], []
    total = 0
    t_start = time.perf_counter()
    for arrival, prompt, max_new in workload:
        now = time.perf_counter() - t_start
        if now < arrival:
            time.sleep(arrival - now)
        service_start = time.perf_counter()
        waits.append(max(0.0, service_start - t_start - arrival))
        toks = np.zeros((1, t_pad), np.int32)
        toks[0, :prompt.size] = prompt
        length = prompt.size
        produced = []
        stamps = []
        for _ in range(max_new):
            nxt = int(fwd_next(params, toks, np.int32(length)))
            stamps.append(time.perf_counter())
            produced.append(nxt)
            toks[0, length] = nxt
            length += 1
        total += len(produced)
        all_tokens.append(produced)
        ttfts.append(stamps[0] - (t_start + arrival))
        if len(stamps) > 1:
            tpots.append((stamps[-1] - stamps[0]) / (len(stamps) - 1))
    wall = time.perf_counter() - t_start
    out = {
        "requests": len(workload),
        "total_tokens": total,
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(total / wall, 2),
        "tokens": all_tokens,
    }
    out.update(_req_stats(ttfts, tpots, waits))
    return out


# -- capacity multipliers: prefix caching + GQA (ISSUE 15) ------------------

def make_prefix_workload(n_requests=24, sys_len=24,
                         mean_interarrival_s=0.004, tail_lens=(2, 8),
                         new_tokens=(8, 16), vocab=256, seed=17):
    """A system-prompt-heavy Poisson mix: every request shares one
    ``sys_len``-token system prompt followed by a short unique tail —
    the workload shape prefix caching exists for."""
    import numpy as np
    rng = np.random.RandomState(seed)
    sysp = rng.randint(0, vocab, sys_len).astype(np.int32)
    t = 0.0
    out = []
    for _ in range(n_requests):
        t += float(rng.exponential(mean_interarrival_s))
        tail = rng.randint(0, vocab,
                           int(rng.randint(tail_lens[0],
                                           tail_lens[1] + 1))
                           ).astype(np.int32)
        out.append((t, np.concatenate([sysp, tail]),
                    int(rng.randint(new_tokens[0],
                                    new_tokens[1] + 1))))
    return out


def run_prefix(net, workload=None):
    """The prefix-caching contract (hard-asserted by BENCH_MODE=serve):
    on a prefix-heavy workload with per-request SAMPLING enabled,
    cache-on must (a) hit (> 0 hit-rate), (b) prefill >= 30% fewer
    tokens than cache-off on the SAME workload, (c) emit bit-identical
    tokens (per-request determinism makes sampled tokens comparable
    across engine configs), and (d) keep 1.0 decode dispatch/step with
    0 steady-state recompiles — the caching + sampling machinery rides
    the existing one-donated-program-per-step invariant."""
    from mxnet_tpu.serving import SamplingParams
    if workload is None:
        workload = make_prefix_workload()
    # every other request samples (seeded); the rest stay greedy — the
    # bit-identity contract must hold for BOTH decode modes
    sampling = [None if i % 2 == 0 else
                SamplingParams(temperature=0.8, top_k=24, top_p=0.95,
                               seed=4000 + i)
                for i in range(len(workload))]
    on = run_continuous(net, workload, sampling=sampling)
    off = run_continuous(net, workload, sampling=sampling,
                         prefix_cache=False)
    admissions = on["prefix_hits"] + on["prefix_miss"]
    reduction = (1.0 - on["prefill_tokens"] /
                 max(1, off["prefill_tokens"]))
    return {
        "requests": len(workload),
        "tokens_match_cache_off": on.pop("tokens") == off.pop("tokens"),
        "prefill_tokens_on": on["prefill_tokens"],
        "prefill_tokens_off": off["prefill_tokens"],
        "prefill_token_reduction": round(reduction, 4),
        "hit_rate": round(on["prefix_hits"] / max(1, admissions), 4),
        "prefix_hits": on["prefix_hits"],
        "shared_pages": on["prefix_shared_pages"],
        "cow_copies": on["prefix_cow_copies"],
        "sampling_requests": on["sampling_requests"],
        "decode_dispatches_per_step": on["decode_dispatches_per_step"],
        "steady_state_compiles": on["steady_state_compiles"],
        "tokens_per_sec_on": on["tokens_per_sec"],
        "tokens_per_sec_off": off["tokens_per_sec"],
        "ttft_p50_ms_on": on["ttft_p50_ms"],
        "ttft_p50_ms_off": off["ttft_p50_ms"],
    }


# -- speculative decoding (ISSUE 16) ---------------------------------------

def make_spec_workload(net, n_requests=16, mean_interarrival_s=0.004,
                       prompt_lens=(8, 14), new_tokens=(24, 40),
                       pregen=10, vocab=256, seed=29, num_slots=8,
                       page_size=16, max_prefill_len=16,
                       max_seq_len=56):
    """An acceptance-friendly Poisson workload for the speculative
    decoder: every prompt is a short random seed followed by the
    model's OWN greedy continuation (pre-generated once, untimed), so
    the decode chain is self-similar from the first step and the
    n-gram drafter has material to hit — the serving analog of
    templated/system-prompt text, which is what speculative decoding
    exists for.  Same trace for spec-on and spec-off."""
    import numpy as np
    from mxnet_tpu.serving import ServingEngine

    rng = np.random.RandomState(seed)
    seeds = [rng.randint(0, vocab, int(rng.randint(2, 5)))
             .astype(np.int32) for _ in range(n_requests)]
    pre = ServingEngine(net, num_slots=num_slots, page_size=page_size,
                        max_prefill_len=max_prefill_len,
                        max_seq_len=max_seq_len)
    conts = pre.generate(seeds, max_new=pregen)
    t = 0.0
    out = []
    for sd, cont in zip(seeds, conts):
        t += float(rng.exponential(mean_interarrival_s))
        plen = int(rng.randint(prompt_lens[0], prompt_lens[1] + 1))
        prompt = np.concatenate(
            [sd, np.asarray(cont, np.int32)])[:plen].astype(np.int32)
        out.append((t, prompt,
                    int(rng.randint(new_tokens[0], new_tokens[1] + 1))))
    return out


def run_spec(net=None, spec_k=6):
    """The speculative-decoding contract (hard-asserted by
    ``BENCH_MODE=serve``): spec-on vs spec-off on the SAME
    acceptance-friendly workload, same engine geometry, both arms
    driven twice (best wall per arm — single-pass wall on a shared
    box is noisy; tokens must be identical across passes regardless).

    What bench pins on this dict:

    - ``speedup_tokens_per_sec`` >= 1.5 — the tentpole multiplier;
    - ``tokens_per_slot_step`` > 1.3 — tokens per slot participation
      (1.0 == non-speculative by construction);
    - greedy bit-identity: spec-on tokens == spec-off tokens;
    - 1.0 decode dispatch/step and 0 steady-state recompiles with
      spec ON — drafts ride the SAME donated program;
    - counter identity: drafted == accepted + rejected and
      decode tokens == slot_steps + accepted - discarded;
    - sampled reproducibility: a mixed greedy/sampled spec-on run
      repeats bit-identically, and reproduces across a 2-replica
      router failover (``serve.replica.lost``) onto a spun-up
      replacement — the per-request determinism law survives the
      re-decode.

    The probe net is WIDER than the default (d_model 256): the
    speculative program spends extra FLOPs per dispatch to verify k
    drafts, so the win needs dispatch cost to be dominated by model
    compute, exactly as on the real accelerator where decode is
    bandwidth-bound.  See SERVING.md section 2c for when NOT to
    enable."""
    import numpy as np
    from mxnet_tpu import fault
    from mxnet_tpu.serving import (Router, SamplingParams,
                                   ServingEngine, ServingReplica)

    if net is None:
        net = build_net(d_model=256)
    kw = dict(num_slots=8, page_size=16, max_prefill_len=16,
              max_seq_len=56)
    workload = make_spec_workload(net, **kw)

    def arm(k):
        a = run_continuous(net, workload, spec_k=k, **kw)
        b = run_continuous(net, workload, spec_k=k, **kw)
        if a["tokens"] != b["tokens"]:
            raise AssertionError(
                "spec_k=%r emitted different tokens on identical "
                "back-to-back runs" % k)
        return a if a["tokens_per_sec"] >= b["tokens_per_sec"] else b

    on, off = arm(spec_k), arm(0)
    on_tokens, off_tokens = on.pop("tokens"), off.pop("tokens")

    # mixed greedy/sampled determinism: same workload, every other
    # request sampled; two identical runs, then the same requests
    # replayed through a 2-replica router with one replica killed
    # mid-flight — every stream must reproduce bit-exactly
    sampling = [None if i % 2 == 0 else
                SamplingParams(temperature=0.8, top_k=24, top_p=0.95,
                               seed=5000 + i)
                for i in range(len(workload))]
    r1 = run_continuous(net, workload, sampling=sampling,
                        spec_k=spec_k, **kw)
    r2 = run_continuous(net, workload, sampling=sampling,
                        spec_k=spec_k, **kw)
    repro_match = r1["tokens"] == r2["tokens"]

    def mk_replica(rid):
        return ServingReplica(
            ServingEngine(net, spec_k=spec_k, **kw), replica_id=rid)

    rt = Router([mk_replica("sa"), mk_replica("sb")],
                spawn=lambda: mk_replica("s-replacement"),
                max_retries=2)
    rrs = [rt.submit(p, m, sampling=sp)
           for (_, p, m), sp in zip(workload, sampling)]
    fault.configure("serve.replica.lost:1")
    try:
        steps = 0
        while not rt.idle and steps < 10000:
            rt.step()
            steps += 1
    finally:
        fault.reset()
    failover_tokens = [list(map(int, rr.tokens)) for rr in rrs]
    failover_match = failover_tokens == r1["tokens"]

    dec_on = on["total_tokens"] - on["prefill_dispatches"]
    return {
        "requests": len(workload),
        "spec_k": spec_k,
        "speedup_tokens_per_sec": round(
            on["tokens_per_sec"] / off["tokens_per_sec"], 3),
        "tokens_per_sec_on": on["tokens_per_sec"],
        "tokens_per_sec_off": off["tokens_per_sec"],
        "tokens_match_spec_off": on_tokens == off_tokens,
        "tokens_per_slot_step": on["tokens_per_slot_step"],
        "decode_steps_on": on["decode_steps"],
        "decode_steps_off": off["decode_steps"],
        "decode_dispatches_per_step": on["decode_dispatches_per_step"],
        "steady_state_compiles": on["steady_state_compiles"],
        "draft_tokens": on["spec_draft_tokens"],
        "accepted": on["spec_accepted"],
        "rejected": on["spec_rejected"],
        "rollbacks": on["spec_rollbacks"],
        "acceptance_rate": round(
            on["spec_accepted"] / max(1, on["spec_draft_tokens"]), 4),
        "counter_identity_draft": on["spec_draft_tokens"]
        == on["spec_accepted"] + on["spec_rejected"],
        "counter_identity_tokens": dec_on
        == on["spec_slot_steps"] + on["spec_accepted"]
        - on["spec_discarded"],
        "spec_off_drafted": off["spec_draft_tokens"],
        "sampled_repro_match": repro_match,
        "failover_completed": sum(1 for rr in rrs
                                  if rr.state == "completed"),
        "failover_failovers": rt.failovers,
        "failover_tokens_match": failover_match,
    }


def run_gqa(net, pool_pages=13):
    """The GQA capacity contract (hard-asserted by BENCH_MODE=serve):
    at ``K_kv = H/2`` the SAME page-pool byte budget holds >= 1.5x the
    resident sequences (page bytes scale with K_kv, so the budget buys
    2x pages), with kernel-vs-oracle equivalence at mixed lengths."""
    import numpy as np
    from mxnet_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    from mxnet_tpu.serving import ServingEngine

    n_heads = net.blocks._children[0].attn._num_heads
    assert n_heads % 2 == 0, n_heads
    rng = np.random.RandomState(23)

    # kernel-vs-oracle at K_kv = H/2, mixed ragged lengths
    s, d, page, n_pages, mp = 5, 16, 8, 16, 4
    kv = n_heads // 2
    q = rng.randn(s, n_heads, d).astype(np.float32)
    kp = rng.randn(n_pages, page, kv, d).astype(np.float32)
    vp = rng.randn(n_pages, page, kv, d).astype(np.float32)
    perm = rng.permutation(n_pages - 1) + 1
    ctx_lens = [29, 5, 0, 17, 32]
    bt = np.zeros((s, mp), np.int32)
    k = 0
    for i in range(s):
        need = -(-max(1, ctx_lens[i]) // page)
        bt[i, :need] = perm[k:k + need]
        k += need
    ctx = np.asarray(ctx_lens, np.int32)
    out = np.asarray(paged_attention(q, kp, vp, bt, ctx))
    ref = np.asarray(paged_attention_reference(q, kp, vp, bt, ctx))
    kernel_err = float(np.abs(out - ref).max())

    # resident capacity at the same pool bytes: identical worst-case
    # requests, count concurrent residents (prefix cache off — unique
    # prompts are the honest capacity baseline)
    kw = dict(num_slots=16, page_size=16, max_prefill_len=32,
              max_seq_len=48, prefix_cache=False)

    def residents(kv_heads, num_pages):
        eng = ServingEngine(net, kv_heads=kv_heads,
                            num_pages=num_pages, **kw)
        pool_bytes = sum(kc.nbytes + vc.nbytes for kc, vc in eng._kv)
        for _ in range(16):
            eng.submit(rng.randint(0, 256, (32,)).astype(np.int32), 16)
        eng.step()
        occ = eng.sched.occupancy
        eng.run_until_idle()
        return occ, pool_bytes

    occ_mha, bytes_mha = residents(n_heads, pool_pages)
    occ_gqa, bytes_gqa = residents(n_heads // 2, 2 * pool_pages - 1)
    return {
        "kv_heads": n_heads // 2,
        "n_heads": n_heads,
        "kernel_max_err": kernel_err,
        "residents_mha": occ_mha,
        "residents_gqa": occ_gqa,
        "resident_multiplier": round(occ_gqa / max(1, occ_mha), 3),
        "pool_bytes_mha": bytes_mha,
        "pool_bytes_gqa": bytes_gqa,
        "kv_bytes_per_token_ratio": round(bytes_gqa / bytes_mha, 4),
    }


def run_kvq(net, workload, reference_tokens, pool_pages=13):
    """The quantized KV-page contract (ISSUE 20, hard-asserted by
    BENCH_MODE=serve): int8 pages + per-page-per-KV-head fp32 absmax
    scales vs bf16 pools on the SAME Poisson workload —

    - kernel-vs-oracle dequant error <= the pinned tolerance (the
      Pallas kernels and the jnp reference dequantize the SAME int8
      pools + scales; published as the ``serving.kv.quant_error``
      gauge);
    - >= 1.8x resident sequences in the same pool bytes at int8 vs
      bf16 (the scale rows cost ~K_kv*8 bytes/page against the
      2*page*K_kv*D payload halving);
    - greedy token match-rate >= 0.99 vs the fp32 reference (greedy
      under quantization is pinned to ITSELF — bit-identity to the fp
      path is explicitly NOT the law, the match-rate gate is);
    - 1.0 decode dispatch/step and 0 steady-state recompiles with
      int8 pools (quantize-on-scatter lives INSIDE the one donated
      program)."""
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    from mxnet_tpu.serving import ServingEngine

    n_heads = net.blocks._children[0].attn._num_heads
    rng = np.random.RandomState(31)

    # kernel-vs-oracle on the SAME quantized pools: absmax-quantize
    # random fp pages per page per KV head, run both readers
    s, d, page, n_pages, mp = 5, 16, 8, 16, 4
    q = rng.randn(s, n_heads, d).astype(np.float32)

    def quantize(pool):
        scale = (np.abs(pool).max(axis=(1, 3)) / 127.0).astype(
            np.float32)                      # [n_pages, K_kv]
        qp = np.clip(np.round(
            pool / np.maximum(scale, 1e-30)[:, None, :, None]),
            -127, 127).astype(np.int8)
        return qp, scale

    kq, ks = quantize(rng.randn(n_pages, page, n_heads, d)
                      .astype(np.float32))
    vq, vs = quantize(rng.randn(n_pages, page, n_heads, d)
                      .astype(np.float32))
    perm = rng.permutation(n_pages - 1) + 1
    ctx_lens = [29, 5, 0, 17, 32]
    bt = np.zeros((s, mp), np.int32)
    k = 0
    for i in range(s):
        need = -(-max(1, ctx_lens[i]) // page)
        bt[i, :need] = perm[k:k + need]
        k += need
    ctx = np.asarray(ctx_lens, np.int32)
    out = np.asarray(paged_attention(q, kq, vq, bt, ctx,
                                     k_scales=ks, v_scales=vs))
    ref = np.asarray(paged_attention_reference(q, kq, vq, bt, ctx,
                                               k_scales=ks,
                                               v_scales=vs))
    dequant_err = float(np.abs(out - ref).max())
    telemetry.gauge("serving.kv.quant_error").set(dequant_err)

    # resident capacity in the same pool bytes: identical worst-case
    # requests; the int8 pool buys ~2x the pages of the bf16 budget
    kw = dict(num_slots=16, page_size=16, max_prefill_len=32,
              max_seq_len=48, prefix_cache=False)

    def residents(kv_dtype, num_pages):
        eng = ServingEngine(net, kv_dtype=kv_dtype,
                            num_pages=num_pages, **kw)
        pool_bytes = sum(sum(a.nbytes for a in entry)
                        for entry in eng._kv)
        for _ in range(16):
            eng.submit(rng.randint(0, 256, (32,)).astype(np.int32), 16)
        eng.step()
        occ = eng.sched.occupancy
        eng.run_until_idle()
        return occ, pool_bytes, eng.kv_bytes_per_token

    occ_bf16, bytes_bf16, bpt_bf16 = residents("bf16", pool_pages)
    d_model = int(net.wte.shape[1])
    bf16_page = 2 * kw["page_size"] * d_model * 2
    int8_page = 2 * kw["page_size"] * d_model + 2 * n_heads * 4
    int8_pages = pool_pages * bf16_page // int8_page
    occ_int8, bytes_int8, bpt_int8 = residents("int8", int8_pages)

    # the same open-loop workload through an int8 engine: match-rate
    # vs the fp32 reference tokens + the hot-path contracts.  Pages of
    # 8 keep the absmax scale groups tight (one fp32 scale per 8 rows
    # per KV head); the fp reference stands across page sizes — greedy
    # fp tokens are page-layout-invariant (the paged kernel's
    # per-page partial sums reduce in fp32)
    cont = run_continuous(net, workload, page_size=8, kv_dtype="int8")
    matched = total = 0
    for got, want in zip(cont.pop("tokens"), reference_tokens):
        total += len(want)
        matched += sum(1 for a, b in zip(got, want) if a == b)
    return {
        "kv_dtype": "int8",
        "dequant_max_err": dequant_err,
        "residents_bf16": occ_bf16,
        "residents_int8": occ_int8,
        "resident_multiplier": round(occ_int8 / max(1, occ_bf16), 3),
        "pool_bytes_bf16": bytes_bf16,
        "pool_bytes_int8": bytes_int8,
        "bytes_per_token_bf16": round(bpt_bf16, 2),
        "bytes_per_token_int8": round(bpt_int8, 2),
        "bytes_per_token_ratio": round(bpt_int8 / bpt_bf16, 4),
        "token_match_rate": round(matched / max(1, total), 4),
        "tokens_per_sec": cont["tokens_per_sec"],
        "decode_dispatches_per_step":
            cont["decode_dispatches_per_step"],
        "steady_state_compiles": cont["steady_state_compiles"],
    }


# -- degraded mode: kill a replica mid-probe (ISSUE 11 + 13) ---------------

def run_degraded(net, workload, reference_tokens, num_slots=8,
                 page_size=16, max_prefill_len=32, max_seq_len=48,
                 kill_after_steps=3):
    """The survivability contract under replica loss: a 2-replica
    router serving the SAME workload, one replica killed mid-probe
    (``serve.replica.lost``).  Hard contracts asserted by
    ``BENCH_MODE=serve``:

    - ZERO dropped accepted requests — every one completes exactly once;
    - tokens bit-identical to the unfaulted continuous run (greedy
      determinism survives the failover re-decode);
    - the replacement replica spins up AOT-warm: 0 foreground compiles
      (in-process memo / shared AOT cache tier);
    - per-VERDICT deltas, not just totals: 0 ``failed``, and exactly
      the killed replica's in-flight count ``retried``;
    - the whole drill runs against a REAL artifact tree (telemetry
      stream + router journal, the launch.py run-dir layout) and
      ``serve_report`` must reconstruct it: every accepted request one
      terminal verdict, failed-over requests linked across both
      replicas by trace id, the killed replica named in the blame
      section, the merged chrome trace one loadable file, traced
      tokens == serving.tokens bit-exactly.
    """
    from mxnet_tpu import fault, profiler, telemetry
    from mxnet_tpu.serving import Router, ServingEngine, ServingReplica
    import serve_report

    kw = dict(num_slots=num_slots, page_size=page_size,
              max_prefill_len=max_prefill_len, max_seq_len=max_seq_len)
    spawn_compiles = []

    def spawn():
        c0 = profiler.step_stats()["compile_count"]
        rep = ServingReplica(ServingEngine(net, **kw),
                             replica_id="replacement")
        spawn_compiles.append(
            profiler.step_stats()["compile_count"] - c0)
        return rep

    # the run-dir artifact layout (tools/launch.py contract): stream +
    # router journal under <run-dir>/telemetry/
    tree = tempfile.mkdtemp(prefix="serve-degraded-")
    tdir = os.path.join(tree, "telemetry")
    os.makedirs(tdir)
    telemetry.reset()   # the earlier probe phases' events are not ours
    telemetry.start_emitter(os.path.join(tdir, "stream-slot0.jsonl"),
                            interval=0.25)
    replicas = [ServingReplica(ServingEngine(net, **kw),
                               replica_id="a"),
                ServingReplica(ServingEngine(net, **kw),
                               replica_id="b")]
    rt = Router(replicas, spawn=spawn, max_retries=2,
                journal_path=os.path.join(
                    tdir, "router-journal-slot0.jsonl"))
    t_start = time.perf_counter()
    rrs = []
    pending = list(workload)
    steps = 0
    killed = False
    victim_inflight = None
    while pending or not rt.idle:
        now = time.perf_counter() - t_start
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.pop(0)
            rrs.append(rt.submit(prompt, max_new))
        if steps == kill_after_steps and not killed:
            # snapshot each replica's accepted in-flight count BEFORE
            # the killing step: the victim's count is exactly what the
            # router must retry (the per-verdict contract)
            inflight = {id(r): sum(1 for rr in rrs
                                   if rr.state == "accepted"
                                   and rr._home is r)
                        for r in replicas}
            fault.configure("serve.replica.lost:1")
            killed = True
        if rt.step() == 0 and pending:
            time.sleep(min(1e-4, max(0.0, pending[0][0] - now)))
        if killed and victim_inflight is None:
            dead = [r for r in replicas if not r.alive]
            if dead:
                victim_inflight = inflight[id(dead[0])]
                victim_id = dead[0].replica_id
        steps += 1
    fault.reset()
    wall = time.perf_counter() - t_start
    telemetry.stop_emitter()   # final line flushes remaining events
    completed = [rr for rr in rrs if rr.state == "completed"]
    tokens = [rr.tokens for rr in completed]

    # fleet reconstruction from the REAL artifacts
    rep = serve_report.analyze(tree)
    trace_path = os.path.join(tree, "serve-trace.json")
    doc, _t0 = serve_report.merged_trace(rep["data"], rep["requests"])
    with open(trace_path, "w") as f:
        json.dump(doc, f)
    try:
        trace_events = len(json.load(open(trace_path))["traceEvents"])
    except Exception:
        trace_events = 0
    acc = rep["accounting"]
    blamed = {b["replica"] for b in rep["blame"]}
    report = {
        "lifecycle_ok": rep["lifecycle"]["ok"],
        "violations": rep["lifecycle"]["violations"][:5],
        "open_traces": len(rep["lifecycle"]["open_traces"]),
        "arcs": len(rep["arcs"]),
        "linked_arcs": rep["linked_arcs"],
        "killed_replica": victim_id if victim_inflight is not None
        else None,
        "killed_replica_blamed": (victim_id in blamed
                                  if victim_inflight is not None
                                  else False),
        "trace_file_events": trace_events,
        "tokens_counter": acc["tokens"],
        "traced_tokens": acc["traced_tokens"],
        "goodput_counter": acc["goodput"],
        "token_accounting_exact": acc["tokens_match"],
    }
    shutil.rmtree(tree, ignore_errors=True)

    verdicts = {}
    for rr in rrs:
        verdicts[rr.verdict or rr.state] = \
            verdicts.get(rr.verdict or rr.state, 0) + 1
    return {
        "requests": len(rrs),
        "completed": len(completed),
        "dropped": len(rrs) - len(completed),
        "failovers": rt.failovers,
        "replacement_spawns": len(spawn_compiles),
        "replacement_foreground_compiles": sum(spawn_compiles),
        "tokens_match_unfaulted": tokens == reference_tokens,
        "wall_s": round(wall, 4),
        # per-verdict accounting (the degraded contract pins verdicts,
        # not just totals): nothing failed, and the retried count is
        # exactly the victim's in-flight count at the kill
        "verdicts": verdicts,
        "failed": sum(1 for rr in rrs if rr.state == "failed"),
        "retried": sum(1 for rr in rrs if rr.retries > 0),
        "expected_retried": victim_inflight,
        "report": report,
    }


# -- out-of-process fleet drill (ISSUE 14) ---------------------------------

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "..", "serve_worker.py")


def _spawn_worker(run_dir, cache, slot, attempt, extra_env=None):
    """One serve_worker subprocess for ``slot``: shared AOT cache
    (replacements spin up warm), port file under ``run_dir``.  The
    worker drains its variant stores before publishing the port file,
    so 'fleet discoverable' implies 'cache durable'."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXTPU_AOT_CACHE_DIR": cache,
        "JAX_COMPILATION_CACHE_DIR": os.path.join(cache, "xla"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "MXTPU_WORKER_SLOT": str(slot),
        "MXTPU_WORKER_RANK": str(slot),
        "MXTPU_RESTART_ATTEMPT": str(attempt),
        "MXTPU_SERVE_PORT_FILE":
            os.path.join(run_dir, "serve-port-slot%d.json" % slot),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.abspath(_WORKER),
         "--max-seconds", "600"], env=env)


def run_fleet(workload, reference_tokens):
    """The out-of-process fleet drill (``BENCH_MODE=serve`` hard
    contracts, ISSUE 14): REAL worker processes behind the RPC plane.

    Two phases over one spun-up fleet:

    1. **breaker drill** — worker b is armed ``rpc.drop:5`` from
       spawn: its first five RPC replies are blackholed, the proxy's
       calls time out, the circuit breaker TRIPS (placement skips b,
       requests complete on a), then — once the site exhausts — the
       half-open probe succeeds and the breaker CLOSES; post-recovery
       requests are served by b again.  Contracts: every request
       completes, ``trips >= 1``, final state ``closed``, b serves
       after recovery.
    2. **sigkill failover drill** — worker c is armed
       ``serve.replica.sigkill:1``: it dies a REAL SIGKILL on its
       first decode step (mid-probe, with accepted requests in
       flight).  The router confirms the death (pid probe), fails the
       victims over, and the spawn callback brings up a REPLACEMENT
       process on the shared AOT cache.  Contracts: ZERO dropped
       requests, tokens bit-identical to the unfaulted continuous
       run, >= 1 failover, replacement 0 foreground compiles.
    """
    from mxnet_tpu.serving import Router
    from mxnet_tpu.serving.rpc import (BREAKER_CLOSED,
                                       CircuitBreaker,
                                       RpcReplicaProxy,
                                       port_file_path, wait_port_file)

    run_dir = tempfile.mkdtemp(prefix="serve-fleet-")
    cache = os.path.join(run_dir, "aot")
    os.makedirs(cache)
    procs = {}
    try:
        procs["a"] = _spawn_worker(run_dir, cache, 0, 0)
        procs["b"] = _spawn_worker(
            run_dir, cache, 1, 0,
            {"MXTPU_FAULT": "rpc.drop:5",
             "MXTPU_FAULT_ATTEMPTS": "0"})
        procs["c"] = _spawn_worker(
            run_dir, cache, 2, 0,
            {"MXTPU_FAULT": "serve.replica.sigkill:1",
             "MXTPU_FAULT_ATTEMPTS": "0"})
        for slot in (0, 1, 2):
            wait_port_file(port_file_path(run_dir, slot), timeout=300)

        def proxy(slot, rid):
            return RpcReplicaProxy(
                rid, port_file=port_file_path(run_dir, slot),
                timeout_s=0.25, retries=0,
                breaker=CircuitBreaker(threshold=2, cooldown_s=0.4,
                                       name=rid))

        # ---- phase 1: breaker trip + recovery --------------------------
        pa, pb = proxy(0, "a"), proxy(1, "b")
        rt = Router([pa, pb])
        reqs = [rt.submit(p, n) for _t, p, n in workload[:6]]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            rt.step()
            if all(r.done for r in reqs) and \
                    pb.breaker.state == BREAKER_CLOSED and \
                    pb.breaker.trips >= 1:
                break
            time.sleep(0.02)
        tripped, recovered = pb.breaker.trips, \
            pb.breaker.state == BREAKER_CLOSED
        post = [rt.submit(p, n) for _t, p, n in workload[6:10]]
        deadline = time.monotonic() + 60
        while not all(r.done for r in post) and \
                time.monotonic() < deadline:
            rt.step()
            time.sleep(0.02)
        breaker = {
            "completed": sum(1 for r in reqs + post
                             if r.state == "completed"),
            "requests": len(reqs) + len(post),
            "trips": tripped,
            "recovered": recovered,
            "final_state": pb.breaker.state,
            "served_by_b_after_recovery": sum(
                1 for r in post if r.state == "completed"
                and r.replica_id == "b"),
        }

        # ---- phase 2: SIGKILL one replica mid-probe --------------------
        pc = proxy(2, "c")
        spawn_compiles = []

        def spawn():
            # the real supervised-respawn move: a fresh worker process
            # for slot 2, then the successor proxy pinned to it
            procs["c2"] = _spawn_worker(run_dir, cache, 2, 1)
            fresh = pc.successor(replica_id="c2", timeout=300)
            # the 0-foreground-compile contract must be MEASURED, not
            # defaulted: an unreachable health probe is a failed
            # drill, never a silent 0
            compiles = None
            for _ in range(20):
                health = fresh.health()
                compiles = (health.get("remote")
                            or {}).get("serve_compiles")
                if compiles is not None:
                    break
                time.sleep(0.25)
            if compiles is None:
                raise RuntimeError(
                    "replacement health probe never answered — the "
                    "foreground-compile contract cannot be verified: "
                    "%r" % (health,))
            spawn_compiles.append(compiles)
            return fresh

        rt2 = Router([pa, pc], spawn=spawn, max_retries=2)
        rrs = []
        pending = list(workload)
        t_start = time.perf_counter()
        while pending or not rt2.idle:
            now = time.perf_counter() - t_start
            while pending and pending[0][0] <= now:
                _, prompt, max_new = pending.pop(0)
                rrs.append(rt2.submit(prompt, max_new))
            # reap exited children: a SIGKILLed worker must become a
            # ProcessLookupError for the proxy's death probe, not a
            # zombie that still answers kill(pid, 0)
            for p in procs.values():
                p.poll()
            if rt2.step() == 0 and pending:
                time.sleep(min(1e-4, max(0.0, pending[0][0] - now)))
            if time.perf_counter() - t_start > 300:
                raise RuntimeError("fleet drill did not drain")
        completed = [rr for rr in rrs if rr.state == "completed"]
        tokens = [rr.tokens for rr in completed]
        return {
            "requests": len(rrs),
            "completed": len(completed),
            "dropped": len(rrs) - len(completed),
            "failovers": rt2.failovers,
            "tokens_match_unfaulted": tokens == reference_tokens,
            "replacement_spawns": len(spawn_compiles),
            "replacement_foreground_compiles":
                sum(c or 0 for c in spawn_compiles),
            "retried": sum(1 for rr in rrs if rr.retries > 0),
            "breaker": breaker,
        }
    finally:
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(run_dir, ignore_errors=True)


def run_partition(workload, reference_tokens):
    """The ISSUE-17 partition drill: RPC-native liveness over a fleet
    that shares NO run directory.  Every worker lives in a PRIVATE tmp
    dir — its port file, heartbeat file, and telemetry are invisible
    to its peers and to the router except for ONE bootstrap read of
    the port file (the out-of-band discovery stand-in); after that the
    proxies are addr-pinned and liveness rides the heartbeat RPC
    alone.  The only shared artifact is the router host's own journal
    — the multi-host seam.

    Phase A — **heartbeat-only loss** (``rpc.heartbeat.drop``, armed
    mid-run over the drill-plane ``inject`` RPC): worker b's heartbeat
    replies park while its data plane keeps answering.  Laws: the
    proxy records SUSPICION (``rpc.suspicions`` delta > 0), every
    request completes, suspicion CLEARS when the control plane heals,
    and there are ZERO failovers — breaker wobble or a cut control
    plane alone never kills a replica that is still doing work.

    Phase B — **real partition** (``rpc.partition``, a FINITE count so
    the link heals once the armed budget is parked away): worker b
    blackholes every inbound frame while holding accepted work.  The
    proxy suspects, then confirms ``fence_expiry`` (heartbeat AND
    progress silence past the lease); the router fails over, bumps the
    slot's fencing epoch, and re-places the victims on a.  The zombie
    keeps decoding behind the partition; when the link heals, its late
    completions are observed and REJECTED (``rpc.fenced_results``,
    journaled ``fenced`` lines).  Laws: >= 1 failover with the typed
    ``fence_expiry`` reason, >= 1 fenced result, EXACTLY one terminal
    journal line per rid (0 double-delivered), and the delivered
    tokens bit-identical to the unfaulted run.

    **Telemetry plane (ISSUE 18)** rides the same drill: the workers
    export no ``MXTPU_TELEMETRY`` (their private tmp dirs hold no
    stream files), so the ONLY way the router host assembles fleet
    telemetry is the ``telemetry_pull`` RPC — a collector loop in both
    phases appends each worker's pulled lines to
    ``<router_dir>/telemetry/stream-{a,b}.jsonl``, the router process
    runs the default alert rules locally (its proxies own the breaker
    and fence evidence, so ``breaker_open`` / ``replica_fenced`` fire
    HERE) and emits its own line into the same tree, and
    ``serve_report.analyze`` over that pull-only tree must be green:
    lawful lifecycles, traced-vs-counter token accounting bit-exact
    (the zombie's behind-the-partition decode included — its stream is
    pulled after the heal), and >= 1 fired alert in the alerts lane.
    ``fleet_top.collect_matrix`` against the live fleet must return a
    complete matrix (every row up with an engine block)."""
    import io

    import fleet_top as _ft
    import serve_report as _sr
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import Router
    from mxnet_tpu.serving.rpc import (CircuitBreaker, RpcReplicaProxy,
                                       collect_telemetry,
                                       port_file_path, rpc_call,
                                       wait_port_file)

    def cval(name):
        return telemetry.counter(name).value

    def inject(addr, spec, timeout=1.0):
        return rpc_call(tuple(addr), {"method": "inject",
                                      "spec": spec},
                        timeout, retries=0)

    # clean registry in the router process: the pulled tree gets the
    # router's OWN stream line too, and stale counters from earlier
    # in-process probes would break the bit-exact reconciliation
    telemetry.reset()
    cache = tempfile.mkdtemp(prefix="serve-part-aot-")
    router_dir = tempfile.mkdtemp(prefix="serve-part-router-")
    journal = os.path.join(router_dir, "router-journal.jsonl")
    tel_dir = os.path.join(router_dir, "telemetry")
    os.makedirs(tel_dir)
    tel_cursors = {}
    tel_stats = {"lines": 0, "errors": 0, "resets": 0}
    dirs, procs, addrs = {}, {}, {}

    def pull_workers(timeout=0.2):
        # the collector: cursor-resumed telemetry_pull per worker into
        # the router host's tree.  A partitioned worker's pull parks
        # (counted, never fatal) — the client-held cursor makes the
        # post-heal retry pick up exactly where the last one ended
        for tag, addr in addrs.items():
            path = os.path.join(tel_dir, "stream-%s.jsonl" % tag)
            try:
                res = collect_telemetry(
                    path, tuple(addr), cursor=tel_cursors.get(tag),
                    timeout_s=timeout)
                tel_cursors[tag] = res["cursor"]
                tel_stats["lines"] += res["lines"]
                tel_stats["resets"] += res["resets"]
            except Exception:
                tel_stats["errors"] += 1

    try:
        for slot, tag in ((0, "a"), (1, "b")):
            dirs[tag] = tempfile.mkdtemp(
                prefix="serve-part-w%d-" % slot)
            procs[tag] = _spawn_worker(
                dirs[tag], cache, slot, 0,
                {"MXTPU_RPC_ALLOW_INJECT": "1"})
        for slot, tag in ((0, "a"), (1, "b")):
            doc = wait_port_file(port_file_path(dirs[tag], slot),
                                 timeout=300)
            addrs[tag] = (doc.get("host", "127.0.0.1"),
                          int(doc["port"]))

        def proxy(tag):
            # addr-pinned: NO port-file watching after bootstrap —
            # liveness evidence is the heartbeat RPC only
            return RpcReplicaProxy(
                tag, addr=addrs[tag], timeout_s=0.25, retries=0,
                heartbeat_s=0.05, suspect_after_s=0.2,
                dead_after_s=0.8,
                breaker=CircuitBreaker(threshold=1, cooldown_s=100.0,
                                       name=tag))

        pa, pb = proxy("a"), proxy("b")
        rt = Router([pa, pb], journal_path=journal, max_retries=2)

        # ---- phase A: control plane cut, data plane healthy ----------
        base_susp = cval("rpc.suspicions")
        inject(addrs["b"], "rpc.heartbeat.drop:100000")
        reqs = [rt.submit(p, n) for _t, p, n in workload[:8]]
        suspected_seen = False
        next_pull = time.monotonic() + 1.0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            rt.step()
            suspected_seen = suspected_seen or pb.suspected
            if time.monotonic() >= next_pull:
                next_pull = time.monotonic() + 1.0
                pull_workers()
                telemetry.check_alerts()
            if all(r.done for r in reqs) and suspected_seen:
                break
            time.sleep(0.01)
        inject(addrs["b"], "")          # heal the control plane
        deadline = time.monotonic() + 30
        while pb.suspected and time.monotonic() < deadline:
            rt.step()
            time.sleep(0.01)
        phase_a = {
            "requests": len(reqs),
            "completed": sum(1 for r in reqs
                             if r.state == "completed"),
            "suspicions": cval("rpc.suspicions") - base_susp,
            "suspect_cleared": not pb.suspected,
            "failovers": rt.failovers,
            "confirm_reason": pb.confirmed_reason,
        }

        # live fleet matrix between phases: both workers healthy again,
        # so every row must come back complete (up, engine block,
        # heartbeat RTT) — the fleet_top --once contract in-process
        matrix = _ft.collect_matrix(
            [(t, tuple(addrs[t])) for t in ("a", "b")], timeout_s=2.0)
        mbuf = io.StringIO()
        _ft.render_matrix(matrix, mbuf)
        fleet_top = {
            "rows": len(matrix["rows"]),
            "complete": all(r.get("up") and r.get("engine")
                            and r.get("hb_rtt_ms") is not None
                            for r in matrix["rows"]),
            "renders": "replica" in mbuf.getvalue(),
        }

        # ---- phase B: real partition + fenced failover ---------------
        base_fenced = cval("rpc.fenced_results")
        base_conf = cval("rpc.confirmations.fence_expiry")
        rrs = [rt.submit(p, n) for _t, p, n in workload]
        on_b = sum(1 for rr in rrs if rr.replica_id == "b")
        if on_b == 0:
            raise RuntimeError(
                "placement never used worker b — the partition would "
                "cut an idle link and drill nothing")
        # finite count: the partition heals once this budget is parked
        # away (heartbeats, the breaker's one probe, the fenced sweep's
        # polls, and the heal-spam below all burn it)
        inject(addrs["b"], "rpc.partition:100")
        healed = False
        next_pull = time.monotonic() + 1.0
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            rt.step()
            for p_ in procs.values():
                p_.poll()
            if time.monotonic() >= next_pull:
                next_pull = time.monotonic() + 1.0
                # b's pulls park while partitioned (each burns one of
                # the armed budget, same as any inbound frame) and
                # resume from the held cursor after the heal
                pull_workers()
                telemetry.check_alerts()
            done = all(rr.done for rr in rrs)
            if done and cval("rpc.fenced_results") - base_fenced >= 1:
                break
            if done and rt.failovers > phase_a["failovers"] \
                    and not healed:
                try:
                    inject(addrs["b"], "", timeout=0.1)
                    healed = True
                except Exception:
                    pass    # still partitioned: the attempt burned one
            time.sleep(0.01)
        completed = [rr for rr in rrs if rr.state == "completed"]
        tokens = [rr.tokens for rr in completed]

        # telemetry finale: make sure the link is healed, then pull
        # each worker to quiescence (cursor stops advancing) — the
        # zombie's behind-the-partition decode must be IN the tree or
        # the traced-vs-counter reconciliation below can't be exact
        try:
            inject(addrs["b"], "", timeout=0.5)
        except Exception:
            pass
        settle = time.monotonic() + 20
        while time.monotonic() < settle:
            before = {t: (tel_cursors.get(t) or {}).get("req_seq")
                      for t in addrs}
            pull_workers(timeout=1.0)
            after = {t: (tel_cursors.get(t) or {}).get("req_seq")
                     for t in addrs}
            if after == before and all(v is not None
                                       for v in after.values()):
                break
            time.sleep(0.2)
        telemetry.check_alerts()
        # the router host's own line joins the same tree: its registry
        # holds the fleet-level events (submits, finals, fenced, the
        # alerts its rules fired) the workers never see
        telemetry._emit_line(
            os.path.join(tel_dir, "stream-router.jsonl"), final=True)

        # serve_report over the PULL-ONLY tree (the workers' private
        # dirs were never read): green or the drill fails
        rep = _sr.analyze(router_dir)
        rbuf = io.StringIO()
        _sr.render(rep, rbuf)
        acc = rep["accounting"]
        telemetry_out = {
            "pulled_lines": tel_stats["lines"],
            "pull_errors": tel_stats["errors"],
            "cursor_resets": tel_stats["resets"],
            "streams": sorted(os.listdir(tel_dir)),
            "lifecycle_ok": rep["lifecycle"]["ok"],
            "accounting_exact": bool(acc["tokens_match"]),
            "tokens": acc["tokens"],
            "traced_tokens": acc["traced_tokens"],
            "alerts_fired": len(rep["alerts"]),
            "alert_rules": sorted({a["rule"] for a in rep["alerts"]
                                   if a["rule"]}),
            "report_renders": "fired alerts" in rbuf.getvalue(),
            "fleet_top": fleet_top,
        }

        # exactly-once off the journal: one terminal line per rid,
        # fenced lines are separate typed events, never deliveries
        terminal = {}
        fenced_lines = []
        with open(journal) as f:
            for ln in f:
                try:
                    doc = json.loads(ln)
                except ValueError:
                    continue
                if doc.get("event") == "fenced":
                    fenced_lines.append(doc)
                elif doc.get("event") == "complete":
                    terminal[doc["rid"]] = \
                        terminal.get(doc["rid"], 0) + 1
        return {
            "phase_a": phase_a,
            "requests": len(rrs),
            "completed": len(completed),
            "dropped": len(rrs) - len(completed),
            "failovers": rt.failovers,
            "confirm_reason": pb.confirmed_reason,
            "confirmations_fence_expiry":
                cval("rpc.confirmations.fence_expiry") - base_conf,
            "fenced_results":
                cval("rpc.fenced_results") - base_fenced,
            "fenced_journal_lines": len(fenced_lines),
            "double_delivered":
                sum(1 for v in terminal.values() if v > 1),
            "victims_on_partitioned": on_b,
            "tokens_match_unfaulted": tokens == reference_tokens,
            "telemetry": telemetry_out,
        }
    finally:
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        for d in list(dirs.values()) + [cache, router_dir]:
            shutil.rmtree(d, ignore_errors=True)


# -- streamed delivery drills (ISSUE 19) -----------------------------------

def run_streamed(net, workload, num_slots=8, page_size=16,
                 max_prefill_len=32, max_seq_len=48):
    """In-process streamed-delivery phase: an open-loop workload where
    every in-flight request is POLLED once per engine step (the
    client-pull cadence) and its tokens assembled strictly by cursor.
    What ``BENCH_MODE=serve`` pins on this dict:

    - exactly-once assembly: the cursor-assembled streams equal the
      engine's own token lists bit-for-bit (no gap, no dup);
    - the hot path survives streaming: 1.0 decode dispatch/step and 0
      steady-state recompiles WITH a poll per request per step — the
      delivery plane never forces a dispatch;
    - streamed TTFT p50 < 0.5x the unary completion p50: first-token
      latency is now a client-visible number, not a telemetry-only one
      (a unary client waits for completion).

    The latency split runs on a streaming-REPRESENTATIVE trace
    (arrival rate the slot pool absorbs, decode-dominated lengths):
    under a saturating burst, queue wait dominates BOTH classes
    equally and the ratio measures the scheduler, not the delivery
    plane — the throughput/queueing contracts already own that regime
    (``run_continuous`` and the fleet drill keep the original burst).
    """
    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.serving import ServingEngine
    import numpy as np

    eng = ServingEngine(net, num_slots=num_slots, page_size=page_size,
                        max_prefill_len=max_prefill_len,
                        max_seq_len=max_seq_len)
    eng.generate([np.zeros(4, np.int32)], max_new=2)
    profiler.reset_step_stats()
    telemetry.reset()
    base = profiler.step_stats()
    d0, c0 = base["dispatch_count"], base["compile_count"]
    steps0, prefills0 = eng.decode_steps, eng.prefills

    reqs, arrivals, assembled = [], [], []
    first_token_t, done_t = [], []
    polls = 0
    pending = list(workload)
    t_start = time.perf_counter()
    while pending or not eng.sched.idle:
        now = time.perf_counter() - t_start
        while pending and pending[0][0] <= now:
            arr, prompt, max_new = pending.pop(0)
            arrivals.append(arr)
            assembled.append([])
            first_token_t.append(None)
            done_t.append(None)
            reqs.append(eng.submit(prompt, max_new))
        if eng.step() == 0 and pending:
            time.sleep(min(1e-4, max(0.0, pending[0][0] - now)))
        # the client-pull cadence: one poll per in-flight stream per
        # step, tokens appended strictly at the held cursor
        for i, req in enumerate(reqs):
            if done_t[i] is not None:
                continue
            reply = eng.poll(req.trace, cursor=len(assembled[i]))
            polls += 1
            t_now = time.perf_counter() - t_start
            if reply["tokens"]:
                if first_token_t[i] is None:
                    first_token_t[i] = t_now
                assembled[i].extend(reply["tokens"])
            if reply["done"] and not reply["more"]:
                done_t[i] = t_now
    # drain the tail: terminal buffers answer re-polls until TTL
    for i, req in enumerate(reqs):
        while done_t[i] is None:
            reply = eng.poll(req.trace, cursor=len(assembled[i]))
            polls += 1
            if first_token_t[i] is None and reply["tokens"]:
                first_token_t[i] = time.perf_counter() - t_start
            assembled[i].extend(reply["tokens"])
            if reply["done"] and not reply["more"]:
                done_t[i] = time.perf_counter() - t_start

    stats = profiler.step_stats()
    decode_steps = eng.decode_steps - steps0
    prefills = eng.prefills - prefills0
    dispatches = stats["dispatch_count"] - d0
    streamed_ttft = sorted(t - a for t, a in zip(first_token_t,
                                                 arrivals))
    unary_done = sorted(t - a for t, a in zip(done_t, arrivals))
    engine_tokens = [[int(t) for t in r.tokens] for r in reqs]
    ttft_p50 = _pct(streamed_ttft, 0.5)
    unary_p50 = _pct(unary_done, 0.5)
    return {
        "requests": len(reqs),
        "polls": polls,
        "exactly_once": assembled == engine_tokens,
        "decode_dispatches_per_step": round(
            (dispatches - prefills) / max(1, decode_steps), 4),
        "steady_state_compiles": stats["compile_count"] - c0,
        "streamed_ttft_p50_ms": round(ttft_p50 * 1e3, 3),
        "streamed_ttft_p99_ms": round(
            _pct(streamed_ttft, 0.99) * 1e3, 3),
        "unary_completion_p50_ms": round(unary_p50 * 1e3, 3),
        "ttft_vs_unary_ratio": round(ttft_p50 / max(1e-9, unary_p50),
                                     4),
        "stream_polls_counter":
            telemetry.counter("serving.stream.polls").value,
        "delivered_counter":
            telemetry.counter("serving.stream.delivered").value,
    }


def run_cancel(net, num_slots=4, page_size=8, max_prefill_len=32,
               max_seq_len=48):
    """Cancellation drill: one request cancelled MID-DECODE, one
    cancelled while QUEUED (slots full), the rest served to
    completion.  Pins: both land the typed terminal verdict
    ``cancelled`` (between decode steps — slot + pages released), the
    survivors' tokens are untouched, cancel is idempotent, and the
    page pool conserves (audit green, all pages back in the free
    pool)."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ServingEngine
    import numpy as np

    rng = np.random.RandomState(41)
    eng = ServingEngine(net, num_slots=num_slots, page_size=page_size,
                        max_prefill_len=max_prefill_len,
                        max_seq_len=max_seq_len, prefix_cache=False)
    eng.generate([np.zeros(4, np.int32)], max_new=2)
    telemetry.reset()
    free0 = eng.alloc.free_pages
    prompts = [rng.randint(0, 256, 8).astype(np.int32)
               for _ in range(num_slots + 1)]
    # reference: the same prompts served with no cancellation
    ref = eng.generate(prompts, max_new=16)
    assert eng.alloc.free_pages == free0
    reqs = [eng.submit(p, 16) for p in prompts]
    eng.step()          # residents placed; the last request queues
    victim, queued = reqs[0], reqs[-1]
    assert queued.state == "queued", queued.state
    eng.step()
    mid = eng.cancel(victim.trace)          # mid-decode teardown
    que = eng.cancel(queued.trace)          # queued teardown
    again = eng.cancel(victim.trace)        # idempotent re-cancel
    eng.run_until_idle()
    eng.alloc.assert_conservation()
    survivors = [r for r in reqs if r is not victim and r is not queued]
    surv_ok = all(
        [int(t) for t in r.tokens] == [int(t) for t in ref[i + 1]]
        for i, r in enumerate(survivors))
    return {
        "mid_decode_verdict": mid["verdict"],
        "queued_verdict": que["verdict"],
        "idempotent": again["verdict"] == mid["verdict"],
        "victim_tokens_at_cancel": mid["tokens"],
        "survivors_completed": sum(1 for r in survivors
                                   if r.state == "finished"),
        "survivor_tokens_match": surv_ok,
        "cancelled_counter":
            telemetry.counter("serving.stream.cancelled").value,
        "pages_restored": eng.alloc.free_pages == free0,
        "conservation_ok": True,
    }


def run_vanish(net, num_slots=4, page_size=8, max_prefill_len=32,
               max_seq_len=48, abandon_s=0.05):
    """The ``serve.client.vanish`` drill: every request's poller runs
    for a few steps (the requests become STREAMS), then the armed
    fault silences two of them — their clients vanish without a
    cancel.  After ``MXTPU_SERVE_ABANDON_S`` of poll silence the
    engine reclaims both with the typed ``abandoned`` verdict; the
    drill pins the reclaim count, the verdicts, conservation (audit
    green + every page back in the free pool — a vanished client can
    NOT pin the KV pool), the surviving streams' bit-exact delivery,
    and the ``orphan_reclaim`` default alert rule firing on the
    counter."""
    from mxnet_tpu import fault, telemetry
    from mxnet_tpu.serving import ServingEngine
    import numpy as np

    rng = np.random.RandomState(43)
    os.environ["MXTPU_SERVE_ABANDON_S"] = str(abandon_s)
    try:
        eng = ServingEngine(net, num_slots=num_slots,
                            page_size=page_size,
                            max_prefill_len=max_prefill_len,
                            max_seq_len=max_seq_len,
                            prefix_cache=False)
    finally:
        del os.environ["MXTPU_SERVE_ABANDON_S"]
    eng.generate([np.zeros(4, np.int32)], max_new=2)
    telemetry.reset()
    free0 = eng.alloc.free_pages
    reqs = [eng.submit(rng.randint(0, 256, 8).astype(np.int32), 24)
            for _ in range(num_slots)]
    assembled = [[] for _ in reqs]
    vanished = set()
    fault.configure("serve.client.vanish:2")
    try:
        # a few polled steps first: every request becomes a stream
        for _ in range(3):
            eng.step()
            for i, r in enumerate(reqs):
                assembled[i].extend(
                    eng.poll(r.trace, cursor=len(assembled[i]))
                    ["tokens"])
        deadline = time.monotonic() + 60
        while not eng.sched.idle and time.monotonic() < deadline:
            eng.step()
            for i, r in enumerate(reqs):
                if i in vanished or r.done:
                    continue
                if fault.trigger("serve.client.vanish"):
                    vanished.add(i)   # this poller goes silent forever
                    continue
                assembled[i].extend(
                    eng.poll(r.trace, cursor=len(assembled[i]))
                    ["tokens"])
            # the reclaim clock is real time; the engine steps faster
            # than abandon_s on CPU, so give the sweep a chance to see
            # the silence age past the window
            time.sleep(abandon_s / 4)
    finally:
        fault.reset()
    eng.alloc.assert_conservation()
    fired = telemetry.check_alerts()
    survivors = [i for i in range(len(reqs)) if i not in vanished]
    for i in survivors:     # drain the survivors' stream tails
        reply = eng.poll(reqs[i].trace, cursor=len(assembled[i]))
        assembled[i].extend(reply["tokens"])
    snap = eng.snapshot()["stream"]
    return {
        "requests": len(reqs),
        "orphans": len(vanished),
        "abandoned_verdicts": sum(1 for i in vanished
                                  if reqs[i].verdict == "abandoned"),
        "abandoned_counter":
            telemetry.counter("serving.stream.abandoned").value,
        "snapshot_abandoned": snap["abandoned"],
        "survivors_completed": sum(
            1 for i in survivors if reqs[i].state == "finished"),
        "survivor_streams_exact": all(
            assembled[i] == [int(t) for t in reqs[i].tokens]
            for i in survivors),
        "pages_restored": eng.alloc.free_pages == free0,
        "conservation_ok": True,
        "alert_fired": any(a.get("rule") == "orphan_reclaim"
                           for a in fired),
    }


def run_stream_fleet(workload, reference_tokens):
    """The kill-mid-stream drill (the ISSUE 19 tentpole contract):
    REAL worker processes, clients streaming by cursor through the
    router, a REAL SIGKILL landed mid-stream (injected over the
    drill-plane RPC once tokens are flowing), plus ``serve.stream.drop``
    armed on the survivor to blackhole poll replies.  Hard contracts:

    - exactly-once delivery: every accepted request's cursor-assembled
      stream equals both its completed journal tokens and the
      unfaulted reference, bit-for-bit — NO gap and NO dup across the
      failover (the router maps the client cursor onto the survivor's
      bit-identical re-decode);
    - >= 1 stream had delivered tokens BEFORE the kill and resumed
      across it (the drill killed an ACTIVE stream, not an idle one);
    - a dropped poll reply recovers by an idempotent re-poll at the
      SAME cursor (observed as >= 1 direct proxy poll returning None,
      with the re-poll resuming contiguously);
    - zero dropped requests, >= 1 failover, cancel-free teardown."""
    from mxnet_tpu.serving import Router
    from mxnet_tpu.serving.rpc import (CircuitBreaker, RpcReplicaProxy,
                                       port_file_path, rpc_call,
                                       wait_port_file)

    run_dir = tempfile.mkdtemp(prefix="serve-stream-")
    cache = os.path.join(run_dir, "aot")
    os.makedirs(cache)
    procs, addrs = {}, {}

    def inject(addr, spec, timeout=1.0):
        return rpc_call(tuple(addr), {"method": "inject",
                                      "spec": spec}, timeout,
                        retries=0)

    try:
        procs["a"] = _spawn_worker(run_dir, cache, 0, 0,
                                   {"MXTPU_RPC_ALLOW_INJECT": "1"})
        procs["v"] = _spawn_worker(run_dir, cache, 1, 0,
                                   {"MXTPU_RPC_ALLOW_INJECT": "1"})
        for slot, tag in ((0, "a"), (1, "v")):
            doc = wait_port_file(port_file_path(run_dir, slot),
                                 timeout=300)
            addrs[tag] = (doc.get("host", "127.0.0.1"),
                          int(doc["port"]))

        def proxy(slot, rid):
            return RpcReplicaProxy(
                rid, port_file=port_file_path(run_dir, slot),
                timeout_s=0.25, retries=0,
                breaker=CircuitBreaker(threshold=4, cooldown_s=0.4,
                                       name=rid))

        pa, pv = proxy(0, "a"), proxy(1, "v")
        spawned = []

        def spawn():
            procs["v2"] = _spawn_worker(run_dir, cache, 1, 1)
            fresh = pv.successor(replica_id="v2", timeout=300)
            spawned.append(fresh)
            return fresh

        rt = Router([pa, pv], spawn=spawn, max_retries=2)
        rrs, assembled = [], []
        pending = list(workload)
        killed = False
        drop_armed = False
        drop_seen = 0
        drop_repoll_contiguous = None
        cursors_at_kill = None
        t_start = time.perf_counter()
        while pending or not rt.idle:
            now = time.perf_counter() - t_start
            while pending and pending[0][0] <= now:
                _, prompt, max_new = pending.pop(0)
                rrs.append(rt.submit(prompt, max_new))
                assembled.append([])
            for p in procs.values():
                p.poll()    # reap: SIGKILL must read as a dead pid
            rt.step()
            # the client poller plane: one cursor-pull per in-flight
            # stream per loop, tokens appended strictly at the cursor
            delivered_v = 0
            for i, rr in enumerate(rrs):
                reply = rt.poll(rr.rid, cursor=len(assembled[i]))
                if reply and reply["tokens"]:
                    assert reply["cursor"] == (len(assembled[i])
                                               + len(reply["tokens"]))
                    assembled[i].extend(reply["tokens"])
                if rr.replica_id == "v" and assembled[i]:
                    delivered_v += 1
            # arm the poll-reply blackhole on the survivor once its
            # streams flow: the next 2 direct polls park, the re-poll
            # at the SAME cursor must resume contiguously
            if not drop_armed and any(
                    a and rr.replica_id == "a" and not rr.done
                    for a, rr in zip(assembled, rrs)):
                idx = next(i for i, rr in enumerate(rrs)
                           if assembled[i] and rr.replica_id == "a"
                           and not rr.done)
                inject(addrs["a"], "serve.stream.drop:2")
                drop_armed = True
                cur = len(assembled[idx])
                for _ in range(8):
                    direct = pa.poll(rrs[idx].trace, cursor=cur)
                    if direct is None:
                        drop_seen += 1       # blackholed reply
                        continue
                    if direct.get("known") and direct.get("tokens"):
                        drop_repoll_contiguous = (
                            direct["cursor"]
                            == cur + len(direct["tokens"]))
                        assembled[idx].extend(direct["tokens"])
                    break
            # land the SIGKILL only once the victim is MID-stream:
            # some client cursor on v must already be past 0
            if not killed and delivered_v >= 1:
                cursors_at_kill = [len(a) for a in assembled]
                inject(addrs["v"], "serve.replica.sigkill:1",
                       timeout=0.5)
                killed = True
            if time.perf_counter() - t_start > 300:
                raise RuntimeError("stream fleet drill did not drain")
            time.sleep(0.005)
        # drain every stream tail to its terminal buffer
        for i, rr in enumerate(rrs):
            for _ in range(50):
                reply = rt.poll(rr.rid, cursor=len(assembled[i]))
                if reply is None:
                    break
                assembled[i].extend(reply["tokens"])
                if not reply["more"]:
                    break
        completed = [rr for rr in rrs if rr.state == "completed"]
        journal_tokens = [rr.tokens for rr in completed]
        resumed = sum(
            1 for i, rr in enumerate(rrs)
            if rr.retries > 0 and cursors_at_kill is not None
            and i < len(cursors_at_kill) and cursors_at_kill[i] > 0)
        return {
            "requests": len(rrs),
            "completed": len(completed),
            "dropped": len(rrs) - len(completed),
            "failovers": rt.failovers,
            "killed_mid_stream": killed,
            "streams_resumed_across_kill": resumed,
            "exactly_once": assembled == [rr.tokens for rr in rrs],
            "tokens_match_unfaulted":
                journal_tokens == reference_tokens,
            "drop_blackholed_replies": drop_seen,
            "drop_repoll_contiguous": drop_repoll_contiguous,
            "replacement_spawns": len(spawned),
        }
    finally:
        for p in procs.values():
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(run_dir, ignore_errors=True)


def run_streaming(net, workload, reference_tokens, fleet=True):
    """The ISSUE 19 umbrella: in-process streamed phase + cancel drill
    + vanish drill (+ the out-of-process kill-mid-stream drill).  The
    fleet drill replays the caller's burst ``workload`` against its
    ``reference_tokens``; the streamed latency split gets its own
    decode-dominated trace (see ``run_streamed``) at the same engine
    config, so the AOT memo is shared."""
    stream_workload = make_workload(n_requests=24,
                                    mean_interarrival_s=0.02,
                                    new_tokens=(16, 24), seed=11)
    out = {
        "streamed": run_streamed(net, stream_workload),
        "cancel": run_cancel(net),
        "vanish": run_vanish(net),
    }
    if fleet:
        out["fleet"] = run_stream_fleet(workload, reference_tokens)
    return out


def measure_trace_overhead(slots=8, iters=2000, passes=5):
    """Isolated microbench of the per-decode-step tracing cost: one
    batched ``tokens`` event naming every resident trace (exactly what
    ``ServingEngine.step`` adds per decode step), timed hot, median of
    ``passes``.  ``BENCH_MODE=serve`` asserts it under
    ``MXTPU_SERVE_TRACE_BUDGET_US`` (default 2 µs/decode-step)."""
    from mxnet_tpu import telemetry

    telemetry.reset()
    traces = [telemetry.mint_trace() for _ in range(slots)]
    note = telemetry.note_request_event
    results = []
    for _ in range(passes):
        t0 = time.perf_counter_ns()
        for i in range(iters):
            # list built per step like the engine's comprehension over
            # its residents — the microbench pays what the hot path pays
            note("", "tokens", t_ns=t0,
                 args={"replica": "a", "step": i,
                       "traces": list(traces)})
        results.append((time.perf_counter_ns() - t0) / 1e3 / iters)
        telemetry.reset()
    return round(sorted(results)[len(results) // 2], 3)


def measure_collector_impact(net=None, n_requests=12, iters=200,
                             passes=5):
    """Collector-on-the-hot-path microbench (ISSUE 18): drives the
    engine open-loop while running ``telemetry.pull_snapshot`` — the
    entire ``telemetry_pull`` handler body minus the socket — after
    EVERY engine step, far denser than the supervisor's default 2 s
    interval, and checks the serving hot-path contracts survive
    (exactly 1.0 decode dispatch/step, 0 steady-state recompiles: the
    pull must never force a dispatch or a recompile).  Then times the
    steady-state pull itself hot (cursor caught up: the report
    snapshot dominates), median of ``passes``, for the
    ``MXTPU_TELEMETRY_PULL_BUDGET`` budget (µs, default 2000)."""
    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.serving import ServingEngine
    import numpy as np

    if net is None:
        net = build_net()
    workload = make_workload(n_requests=n_requests)
    eng = ServingEngine(net, num_slots=8, page_size=16,
                        max_prefill_len=32, max_seq_len=48)
    eng.generate([np.zeros(4, np.int32)], max_new=2)
    profiler.reset_step_stats()
    telemetry.reset()
    base = profiler.step_stats()
    d0, c0 = base["dispatch_count"], base["compile_count"]
    steps0, prefills0 = eng.decode_steps, eng.prefills

    cursor = {"req_seq": None, "step_seq": None}
    pulls = 0
    reqs, pending = [], list(workload)
    t_start = time.perf_counter()
    while pending or not eng.sched.idle:
        now = time.perf_counter() - t_start
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.pop(0)
            reqs.append(eng.submit(prompt, max_new))
        if eng.step() == 0 and pending:
            time.sleep(min(1e-4, max(0.0, pending[0][0] - now)))
        _doc, cursor, more = telemetry.pull_snapshot(
            cursor.get("req_seq"), cursor.get("step_seq"))
        pulls += 1
        while more:     # chunked tail, same as a collector's loop
            _doc, cursor, more = telemetry.pull_snapshot(
                cursor.get("req_seq"), cursor.get("step_seq"))
            pulls += 1

    stats = profiler.step_stats()
    decode_steps = eng.decode_steps - steps0
    prefills = eng.prefills - prefills0
    dispatches = stats["dispatch_count"] - d0

    # isolated steady-state pull cost (caught-up cursor, warm registry)
    results = []
    for _ in range(passes):
        t0 = time.perf_counter_ns()
        for _i in range(iters):
            _doc, cursor, _more = telemetry.pull_snapshot(
                cursor.get("req_seq"), cursor.get("step_seq"))
        results.append((time.perf_counter_ns() - t0) / 1e3 / iters)
    return {
        "pulls": pulls,
        "decode_steps": decode_steps,
        "decode_dispatches_per_step": round(
            (dispatches - prefills) / max(1, decode_steps), 4),
        "steady_state_compiles": stats["compile_count"] - c0,
        "pull_us": round(sorted(results)[len(results) // 2], 1),
        "tokens": sum(len(r.tokens) for r in reqs),
    }


# -- AOT-warm replica spin-up (restart_probe pattern) ----------------------

def _spinup_child():
    """One fresh replica: backend-ready -> engine built -> first token.
    Prints foreground serving-program compiles (profiler counters: the
    engine's eager AOT-miss compiles + anything landing inside an
    instrumented serve call) and the time to first token."""
    import numpy as np
    import jax
    jax.devices()
    from mxnet_tpu import aot_cache, profiler, telemetry
    from mxnet_tpu.serving import ServingEngine

    net = build_net()
    profiler.reset_step_stats()
    t0 = time.perf_counter()
    eng = ServingEngine(net, num_slots=4, page_size=8,
                        max_prefill_len=32, max_seq_len=48)
    eng.generate([np.arange(6, dtype=np.int32)], max_new=2)
    ttft = time.perf_counter() - t0
    # background stores (twin serialization) must land before exit or
    # the warm attempt finds an empty cache
    aot_cache.drain(timeout=120)
    c = telemetry.report()["counters"]
    print(json.dumps({
        "ttfb_s": round(ttft, 3),
        "serve_compiles": profiler.step_stats()["compile_count"],
        "aot_hits": c.get("aot.cache_hits", 0),
        "aot_misses": c.get("aot.cache_misses", 0),
    }), flush=True)


def measure_spinup():
    """Cold vs warm replica spin-up sharing one AOT cache dir — what two
    launch.py restart attempts (or two replicas on one host) see."""
    cache = tempfile.mkdtemp(prefix="serve-probe-aot-")
    env = dict(os.environ)
    env.update({
        "MXTPU_AOT_CACHE_DIR": cache,
        "JAX_COMPILATION_CACHE_DIR": os.path.join(cache, "xla"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PLATFORMS": "cpu",
    })
    out = {}
    try:
        for label in ("cold", "warm"):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--spinup-child"],
                env=env, capture_output=True, text=True, timeout=600)
            if r.returncode != 0:
                raise RuntimeError("spinup child (%s) failed rc=%d:\n%s"
                                   % (label, r.returncode,
                                      r.stderr[-2000:]))
            out[label] = json.loads(r.stdout.strip().splitlines()[-1])
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    return {
        "cold_ttfb_s": out["cold"]["ttfb_s"],
        "warm_ttfb_s": out["warm"]["ttfb_s"],
        "cold_serve_compiles": out["cold"]["serve_compiles"],
        "warm_serve_compiles": out["warm"]["serve_compiles"],
        "warm_aot_hits": out["warm"]["aot_hits"],
    }


def run(spinup=True, degraded=True, fleet=True):
    net = build_net()
    workload = make_workload()
    cont = run_continuous(net, workload)
    seq = run_sequential(net, workload)
    cont_tokens = cont.pop("tokens")
    if cont_tokens != seq.pop("tokens"):
        raise AssertionError(
            "continuous and sequential servers emitted different greedy "
            "tokens for the same workload — the paged engine diverged "
            "from the dense forward")
    result = {
        "continuous": cont,
        "sequential": seq,
        "speedup_tokens_per_sec": round(
            cont["tokens_per_sec"] / seq["tokens_per_sec"], 2),
        "trace_overhead_us": measure_trace_overhead(),
        "collector": measure_collector_impact(net),
        "prefix": run_prefix(net),
        "gqa": run_gqa(net),
        "kvq": run_kvq(net, workload, cont_tokens),
        "spec": run_spec(),
        "stream": run_streaming(net, workload, cont_tokens,
                                fleet=fleet),
    }
    if degraded:
        result["degraded"] = run_degraded(net, workload, cont_tokens)
    if fleet:
        result["fleet"] = run_fleet(workload, cont_tokens)
        result["partition"] = run_partition(workload, cont_tokens)
    if spinup:
        result["spinup"] = measure_spinup()
    return result


if __name__ == "__main__":
    if "--spinup-child" in sys.argv:
        _spinup_child()
    else:
        print(json.dumps(run("--no-spinup" not in sys.argv,
                             fleet="--no-fleet" not in sys.argv)))
