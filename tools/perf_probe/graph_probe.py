"""BENCH_MODE=graph probe: the rewrite pipeline's measurable contract.

Builds the two bench graphs (PERF.md §15) as symbols — a ResNet-style
conv→bn→relu residual tower and a post-LN GPT transformer stack whose
attention masks are built symbolically per block — binds each with the
pipeline ON and OFF, and measures:

- **HLO instruction count** of the lowered forward program (the
  pre-optimization module ``jit(...).lower()`` hands XLA): the number
  the graph stage directly controls — what a graph-level rewrite saves
  BEFORE the backend ever sees it.  Contract: >= 15% fewer with the
  pipeline on, for both graphs.  The post-XLA compiled count is
  reported alongside for reference.
- **output equivalence**: pipeline-on forward == pipeline-off forward
  (rtol 1e-6 fp32), eval and train.
- **step-time**: median wall time of the compiled forward, on vs off
  (reported; eval-mode conv+bn folding and constant-folded masks are
  where the win comes from).
- **steptrace invariants with the pipeline enabled**: a short fused fit
  loop over the fusable conv net must hold 1.0 dispatch/step with 0
  steady-state recompiles (the recompile contract).

Prints one JSON document; bench.py BENCH_MODE=graph asserts the
contracts and emits the driver row.

Usage: JAX_PLATFORMS=cpu python tools/perf_probe/graph_probe.py
"""
from __future__ import annotations

import contextlib
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

HLO_CONTRACT = 0.15  # >= 15% fewer lowered-HLO instructions


# ---------------------------------------------------------------------------
# bench graphs
# ---------------------------------------------------------------------------

def build_resnet_sym(blocks=8, filters=16):
    """Conv→BN→ReLU residual tower with a BN'd projection stem and a
    dense head — every unit is the pattern the fuse pass targets."""
    import mxnet_tpu as mx

    def conv_bn_relu(x, name, act=True, **kw):
        x = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1), no_bias=True,
                               num_filter=filters, name="%s_conv" % name,
                               **kw)
        x = mx.sym.BatchNorm(x, fix_gamma=False, name="%s_bn" % name)
        if act:
            x = mx.sym.Activation(x, act_type="relu", name="%s_relu" % name)
        return x

    net = mx.sym.Variable("data")
    net = conv_bn_relu(net, "stem")
    for i in range(blocks):
        inner = conv_bn_relu(net, "b%d_u1" % i)
        inner = conv_bn_relu(inner, "b%d_u2" % i, act=False)
        net = mx.sym.Activation(net + inner, act_type="relu",
                                name="b%d_out" % i)
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         name="gap")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="head_fc")
    net = mx.sym.Activation(net, act_type="relu", name="head_relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="logits")
    return mx.sym.SoftmaxOutput(net, name="softmax"), \
        {"data": (8, 3, 16, 16), "softmax_label": (8,)}


def build_gpt_sym(layers=4, units=64, heads=4, seq=128, vocab=128):
    """Post-LN transformer stack over symbols.  The causal mask is
    constructed SYMBOLICALLY inside every block (arange → reshape →
    compare → scale), exactly the per-layer redundancy an op-by-op
    frontend emits — constant folding evaluates each chain once at bind
    and CSE merges the copies; LayerNorm(x + sublayer) is the
    fused-epilogue pattern; FFN is FullyConnected→gelu."""
    import mxnet_tpu as mx
    d = units // heads

    def causal_bias(name):
        # (T, T) additive bias: 0 where k<=q, -1e9 above the diagonal —
        # parameter-free, so the fold pass turns the whole chain into
        # one literal (and CSE dedups it across blocks first)
        q = mx.sym.Reshape(mx.sym._arange(start=0, stop=seq,
                                          name="%s_qpos" % name),
                           shape=(seq, 1))
        k = mx.sym.Reshape(mx.sym._arange(start=0, stop=seq,
                                          name="%s_kpos" % name),
                           shape=(1, seq))
        keep = mx.sym.broadcast_greater_equal(q, k)  # 1 where visible
        return (keep - 1.0) * 1e9  # 0 visible, -1e9 masked

    def block(x, name):
        # attention sublayer (batched heads via reshape+batch_dot)
        qkv = mx.sym.FullyConnected(x, num_hidden=3 * units, flatten=False,
                                    name="%s_qkv" % name)
        qkv = mx.sym.Reshape(qkv, shape=(-1, seq, 3, heads, d))
        qkv = mx.sym.transpose(qkv, axes=(2, 0, 3, 1, 4))
        q = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=0, begin=0, end=1),
                           shape=(-1, seq, d))
        k = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=0, begin=1, end=2),
                           shape=(-1, seq, d))
        v = mx.sym.Reshape(mx.sym.slice_axis(qkv, axis=0, begin=2, end=3),
                           shape=(-1, seq, d))
        scores = mx.sym.batch_dot(q, k, transpose_b=True) * (d ** -0.5)
        scores = mx.sym.broadcast_add(scores, causal_bias(name))
        att = mx.sym.batch_dot(mx.sym.softmax(scores, axis=-1), v)
        att = mx.sym.Reshape(att, shape=(-1, heads, seq, d))
        att = mx.sym.Reshape(mx.sym.transpose(att, axes=(0, 2, 1, 3)),
                             shape=(-1, seq, units))
        att = mx.sym.FullyConnected(att, num_hidden=units, flatten=False,
                                    name="%s_proj" % name)
        x = mx.sym.LayerNorm(x + att, name="%s_ln1" % name)
        # FFN sublayer
        h = mx.sym.FullyConnected(x, num_hidden=4 * units, flatten=False,
                                  name="%s_fc1" % name)
        h = mx.sym.Activation(h, act_type="gelu", name="%s_gelu" % name)
        h = mx.sym.FullyConnected(h, num_hidden=units, flatten=False,
                                  name="%s_fc2" % name)
        return mx.sym.LayerNorm(x + h, name="%s_ln2" % name)

    tokens = mx.sym.Variable("data")
    h = mx.sym.Embedding(tokens, input_dim=vocab, output_dim=units,
                         name="wte")
    pos = mx.sym._arange(start=0, stop=seq, name="pos_ids")
    h = mx.sym.broadcast_add(
        h, mx.sym.expand_dims(
            mx.sym.Embedding(pos, input_dim=seq, output_dim=units,
                             name="wpe"), axis=0))
    for i in range(layers):
        h = block(h, "h%d" % i)
    h = mx.sym.FullyConnected(h, num_hidden=vocab, flatten=False,
                              name="lm_head")
    return mx.sym.SoftmaxOutput(h, preserve_shape=True, name="softmax"), \
        {"data": (2, seq), "softmax_label": (2, seq)}


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

_INSTR_RE = re.compile(r"^\s+\S+ = ", re.M)


def count_instructions(hlo_text):
    return len(_INSTR_RE.findall(hlo_text))


@contextlib.contextmanager
def pipeline(on):
    prev = os.environ.get("MXTPU_GRAPH_PASSES")
    os.environ["MXTPU_GRAPH_PASSES"] = "" if on else "off"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("MXTPU_GRAPH_PASSES", None)
        else:
            os.environ["MXTPU_GRAPH_PASSES"] = prev


def _bind(sym, shapes, on, type_dict=None):
    import mxnet_tpu as mx
    with pipeline(on):
        return sym.simple_bind(mx.cpu(), grad_req="null",
                               type_dict=type_dict, **shapes)


def _seed_params(exe, shapes, rs):
    import numpy as np
    for name, arr in sorted(exe.arg_dict.items()):
        if name in shapes:
            continue
        arr[:] = rs.randn(*arr.shape).astype(np.float32) * 0.1
    for name, arr in sorted(exe.aux_dict.items()):
        if name.endswith("moving_var"):
            arr[:] = np.abs(rs.randn(*arr.shape).astype(np.float32)) + 0.5
        else:
            arr[:] = rs.randn(*arr.shape).astype(np.float32) * 0.1


def measure_graph(name, sym, shapes, data_fn, train=False, reps=30):
    """Lowered/compiled instruction counts, forward equivalence and
    median step time, pipeline on vs off."""
    import numpy as np
    import jax

    feeds = data_fn()
    sides = {}
    for on in (False, True):
        exe = _bind(sym, shapes, on)
        rs = np.random.RandomState(7)
        _seed_params(exe, shapes, rs)
        for k, v in feeds.items():
            exe.arg_dict[k][:] = v
        plan = exe._plan
        args = {k: v._data for k, v in exe.arg_dict.items()}
        aux = {k: v._data for k, v in exe.aux_dict.items()}
        rng = jax.random.PRNGKey(0)

        def fwd(a, x):
            return plan(a, x, rng, train)[0]

        lowered = jax.jit(fwd).lower(args, aux)
        compiled = lowered.compile()
        out = compiled(args, aux)
        jax.block_until_ready(out)
        sides[on] = {
            "lowered_instructions": count_instructions(lowered.as_text()),
            "compiled_instructions":
                count_instructions(compiled.as_text()),
            "outputs": [np.asarray(o) for o in out],
            "report": exe._graph_report,
            "_call": (compiled, args, aux),
        }
    # interleaved timing (paired off/on segments, median — cancels the
    # slow CPU drift that dwarfs small effects, bench_telemetry style)
    for side in sides.values():
        compiled, args, aux = side["_call"]
        jax.block_until_ready(compiled(args, aux))
    times = {False: [], True: []}
    for _ in range(reps):
        for on in (False, True):
            compiled, args, aux = sides[on]["_call"]
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(args, aux))
            times[on].append(time.perf_counter() - t0)
    for on in (False, True):
        ts = sorted(times[on])
        sides[on]["fwd_ms_p50"] = round(ts[len(ts) // 2] * 1e3, 3)
        del sides[on]["_call"]
    off, on = sides[False], sides[True]
    err = 0.0
    for a, b in zip(off["outputs"], on["outputs"]):
        denom = np.maximum(np.abs(a), 1e-6)
        err = max(err, float(np.max(np.abs(a - b) / denom)))
    reduction = 1.0 - on["lowered_instructions"] / \
        max(1, off["lowered_instructions"])
    return {
        "graph": name,
        "train": train,
        "lowered_instructions_off": off["lowered_instructions"],
        "lowered_instructions_on": on["lowered_instructions"],
        "lowered_reduction": round(reduction, 4),
        "compiled_instructions_off": off["compiled_instructions"],
        "compiled_instructions_on": on["compiled_instructions"],
        "fwd_ms_p50_off": off["fwd_ms_p50"],
        "fwd_ms_p50_on": on["fwd_ms_p50"],
        "fwd_speedup": round(
            off["fwd_ms_p50"] / max(on["fwd_ms_p50"], 1e-9), 3),
        "max_rel_err": err,
        "pass_report": on["report"],
    }


def steptrace_with_pipeline():
    """The recompile contract: a fused fit loop over a FUSABLE net
    (conv→bn→relu stem + dense head) with the pipeline enabled must
    keep the steptrace invariants — 1.0 dispatch/step, 0 steady-state
    compiles."""
    import numpy as np
    import mxnet_tpu as mx
    import steptrace as _steptrace

    rs = np.random.RandomState(0)
    X = rs.randn(4 * 8, 3, 8, 8).astype(np.float32)
    y = rs.randint(0, 4, 4 * 8).astype(np.float32)
    train = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=False,
                              label_name="softmax_label")
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, kernel=(3, 3), pad=(1, 1), num_filter=8,
                             no_bias=True, name="c1")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn1")
    net = mx.sym.Activation(net, act_type="relu", name="r1")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="fa1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    s = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(s, context=mx.cpu())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.05),
                                         ("momentum", 0.9)))
    batches = list(train)
    stats = _steptrace.trace(mod.fit_step, batches)
    stats["fused_patterns"] = (mod.graph_report or {}).get("rewrites")
    return stats


def run():
    import numpy as np
    import jax  # noqa: F401 — fail early off-thread if backend is broken

    rs = np.random.RandomState(3)
    resnet_sym, resnet_shapes = build_resnet_sym()
    gpt_sym, gpt_shapes = build_gpt_sym()

    def resnet_feed():
        return {"data": rs.randn(*resnet_shapes["data"])
                .astype(np.float32)}

    def gpt_feed():
        return {"data": rs.randint(0, 128, gpt_shapes["data"])
                .astype(np.float32)}

    out = {
        "resnet": measure_graph("resnet", resnet_sym, resnet_shapes,
                                resnet_feed),
        "gpt": measure_graph("gpt", gpt_sym, gpt_shapes, gpt_feed),
        "steptrace": steptrace_with_pipeline(),
        "hlo_contract": HLO_CONTRACT,
    }
    return out


if __name__ == "__main__":
    print(json.dumps(run()))
