"""Per-conv layout probe (PERF.md §2) — NOTE: per-op timings through the
tunnel are dispatch-bound; use resnet_probe.py for trustworthy numbers."""
import time, functools
import jax, jax.numpy as jnp
from jax import lax

B = 256
ITERS = 50
cases = [
    (56, 64, 64, 3, 1),
    (56, 256, 64, 1, 1),
    (28, 128, 128, 3, 1),
    (14, 256, 256, 3, 1),
    (7, 512, 512, 3, 1),
]
key = jax.random.PRNGKey(0)

def run(layout, H, Ci, Co, k, s):
    pad = [(k // 2, k // 2)] * 2
    if layout == "NCHW":
        x = jax.random.normal(key, (B, Ci, H, H), jnp.bfloat16)
        w = jax.random.normal(key, (Co, Ci, k, k), jnp.bfloat16)
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        x = jax.random.normal(key, (B, H, H, Ci), jnp.bfloat16)
        w = jax.random.normal(key, (k, k, Ci, Co), jnp.bfloat16)
        dn = ("NHWC", "HWIO", "NHWC")
    dnn = lax.conv_dimension_numbers(x.shape, w.shape, dn)
    conv = functools.partial(lax.conv_general_dilated, window_strides=(s, s),
                             padding=pad, dimension_numbers=dnn)
    # chain ITERS convs so one dispatch measures pure device time; output
    # feeds back (same shape when Ci==Co and s==1; else re-use x)
    @jax.jit
    def loop(x, w):
        def body(i, acc):
            # perturb the input by the running sum so the conv depends on
            # the loop carry — else XLA hoists a loop-invariant conv out
            # (LICM) and the probe reports ITERS-times-too-fast numbers
            xi = acc[0] if Ci == Co and s == 1 else \
                x + acc[1].astype(x.dtype)
            y = conv(xi, w)
            return (y if Ci == Co and s == 1 else acc[0],
                    acc[1] + y.mean().astype(jnp.float32))
        return lax.fori_loop(0, ITERS, body, (x, jnp.float32(0)))
    o = loop(x, w); jax.block_until_ready(o)
    t0 = time.perf_counter()
    o = loop(x, w); jax.block_until_ready(o)
    dt = (time.perf_counter() - t0) / ITERS
    Ho = -(-H // s)
    fl = 2 * B * Ho * Ho * Co * Ci * k * k
    return dt, fl / dt / 1e12

for H, Ci, Co, k, s in cases:
    t1, tf1 = run("NCHW", H, Ci, Co, k, s)
    t2, tf2 = run("NHWC", H, Ci, Co, k, s)
    print("H%-4dCi%-4dCo%-4dk%d  NCHW %7.3fms %6.1fTF/s | NHWC %7.3fms %6.1fTF/s"
          % (H, Ci, Co, k, t1 * 1e3, tf1, t2 * 1e3, tf2))
