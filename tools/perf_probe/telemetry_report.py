"""Render telemetry artifacts for humans.

Each positional argument is a file OR a run directory.  Files are
sniffed per artifact type:

- a JSON-lines timeline written by the periodic emitter
  (``MXTPU_TELEMETRY=path[:interval]``) — one ``report()`` object per
  line (schema ``mxtpu-telemetry-2``; ``-1`` lines from older runs still
  render); the summary covers the LAST line (cumulative totals) and
  notes the line count / wall span, or
- a crash postmortem (schema ``mxtpu-postmortem-2`` / ``-1``) dumped by
  the flight recorder into ``MXTPU_POSTMORTEM_DIR`` — rendered as the
  crash reason, step_stats, fault firings, and the last-K per-step
  table, or
- an elastic membership journal (schema ``mxtpu-membership-1``) written
  by ``tools/launch.py`` into ``<run-dir>/membership.json`` — rendered
  as the world-size transition timeline (attempt starts, failures with
  blamed slot/exit, evictions, re-admissions), or
- a Router audit journal (``router-journal*.jsonl``, schema-less JSON
  lines keyed by request id) — rendered as event/verdict counts and
  failover arcs.  Serving replicas' streams additionally render a
  "serving plane" digest (periodic status line: occupancy, pages, SLO
  state, weights epoch); ``serve_report.py`` merges the fleet.

A **run directory** (``tools/launch.py --run-dir``) renders everything
it holds together — the membership journal, every rank's stream, every
postmortem, and a stall-stacks inventory — so one command digests a
whole job.  ``job_report.py`` (same directory) goes further: it MERGES
the rank streams into one job timeline with straggler blame and a
cross-rank chrome trace; this tool renders each artifact faithfully,
one at a time.

Usage:
    python tools/perf_probe/telemetry_report.py RUN_DIR_OR_FILE ...

See OBSERVABILITY.md for the metric-name and schema contract.
"""
import json
import os
import sys


def _fmt_s(v):
    if v is None:
        return "-"
    if v >= 1.0:
        return "%.2fs" % v
    if v >= 1e-3:
        return "%.2fms" % (v * 1e3)
    return "%.1fus" % (v * 1e6)


def _fmt_n(v):
    return "-" if v is None else ("%.0f" % v)


def _hist_rows(hists):
    rows = []
    for name, h in sorted(hists.items(), key=lambda kv: -kv[1]["sum"]):
        if not h["count"]:
            continue
        # size histograms (ckpt.write_bytes...) render as plain numbers,
        # duration histograms as scaled seconds
        fmt = _fmt_n if "bytes" in name else _fmt_s
        rows.append((name, h["count"], fmt(h["sum"] / h["count"]),
                     fmt(h["p50"]), fmt(h["p90"]), fmt(h["p99"]),
                     fmt(h["max"]), fmt(h["sum"])))
    return rows


def _table(header, rows, out):
    if not rows:
        return
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    for r in [header] + rows:
        out.write("  " + "  ".join(
            str(c).ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")


def _identity_line(doc):
    """`` [rank 1/3 slot 2 attempt 0]`` from a schema-2 identity block
    (empty for schema-1 artifacts / standalone runs)."""
    ident = doc.get("identity") or {}
    if ident.get("rank") is None:
        return ""
    if (ident.get("world_size") or 1) <= 1 and not ident.get("attempt"):
        return ""  # standalone process: no job context to show
    return " [rank %s/%s slot %s attempt %s]" % (
        ident.get("rank"), ident.get("world_size"), ident.get("slot"),
        ident.get("attempt"))


def render_report(doc, out, context=""):
    """Phase-time breakdown + histogram percentiles of one report()."""
    out.write("== telemetry report%s%s ==\n"
              % (_identity_line(doc), context))
    ss = doc.get("step_stats") or {}
    out.write("  steps %s  dispatches %s  compiles %s  skipped %s  "
              "step_ema %s\n" % (
                  ss.get("steps"), ss.get("dispatch_count"),
                  ss.get("compile_count"), ss.get("skipped_steps"),
                  _fmt_s(ss.get("step_time_ema_s"))))
    phases = doc.get("phases") or {}
    total = sum(h["sum"] for h in phases.values())
    # NB: nested spans (ckpt.write encloses ckpt.fsync/rename, etc.)
    # overlap, so the sum exceeds wall time and shares are of the SUM of
    # span time, not of the run
    out.write("\n  phase-time breakdown (summed span time %s; nested "
              "spans overlap):\n" % _fmt_s(total))
    rows = []
    for (name, count, mean, p50, p90, p99, mx, tot) in \
            _hist_rows(phases):
        share = phases[name]["sum"] / total * 100 if total else 0.0
        rows.append((name, count, mean, p50, p99, tot,
                     "%.1f%%" % share))
    _table(("phase", "count", "mean", "p50", "p99", "total", "of-sum"),
           rows, out)
    hists = doc.get("histograms") or {}
    if any(h["count"] for h in hists.values()):
        out.write("\n  histograms:\n")
        _table(("name", "count", "mean", "p50", "p90", "p99", "max",
                "sum"), _hist_rows(hists), out)
    counters = {k: v for k, v in (doc.get("counters") or {}).items() if v}
    if counters:
        out.write("\n  counters: " + "  ".join(
            "%s=%s" % kv for kv in sorted(counters.items())) + "\n")
    gauges = {k: v for k, v in (doc.get("gauges") or {}).items()
              if v is not None}
    if gauges:
        out.write("  gauges: " + "  ".join(
            "%s=%s" % kv for kv in sorted(gauges.items())) + "\n")
    _render_ckpt_pipeline(doc, out)
    _render_io_pipeline(doc, out)
    _render_serving_plane(doc, out)


# phases the step loop actually blocks on under async checkpointing vs
# the work the writer thread absorbs — the split telemetry_report exists
# to make visible (PERF.md §12)
_CKPT_HOT = ("ckpt.save", "ckpt.snapshot", "ckpt.async_wait")
_CKPT_BG = ("ckpt.async_write", "ckpt.write", "ckpt.fsync", "ckpt.rename")


def _render_ckpt_pipeline(doc, out):
    """Checkpoint-pipeline digest: queue depth, save counts, and the
    step-visible stall (hot-path spans) vs background write time.  Note
    ``ckpt.save`` encloses snapshot+enqueue under async but the whole
    write under sync — the per-span rows tell the two apart."""
    c = doc.get("counters") or {}
    phases = doc.get("phases") or {}
    saves = c.get("ckpt.saves", 0)
    if not saves and not any(
            (phases.get(k) or {}).get("count") for k in _CKPT_HOT):
        return
    g = doc.get("gauges") or {}
    out.write("\n  checkpoint pipeline: saves=%d async=%d errors=%d "
              "io_retries=%d queue_depth=%s\n"
              % (saves, c.get("ckpt.async_saves", 0),
                 c.get("ckpt.async_errors", 0),
                 c.get("ckpt.io_retries", 0),
                 g.get("ckpt.queue_depth", "-")))
    rows = []
    for group, names in (("step-visible", _CKPT_HOT),
                         ("background", _CKPT_BG)):
        for name in names:
            h = phases.get(name)
            if not h or not h["count"]:
                continue
            rows.append((name, group, h["count"],
                         _fmt_s(h["sum"] / h["count"]), _fmt_s(h["p50"]),
                         _fmt_s(h["p99"]), _fmt_s(h["max"])))
    _table(("span", "where", "count", "mean", "p50", "p99", "max"),
           rows, out)


# the streaming input plane's phase taxonomy (mxnet_tpu/stream/,
# OBSERVABILITY.md §11): worker-side decode/open phases folded consumer-
# side, plus the two starvation signals a training rank actually blocks
# on — io.queue_wait (consumer starved on the decode result queue) and
# data.prefetch_wait (consumer starved on the device prefetcher)
_IO_PHASES = ("io.queue_wait", "io.decode", "io.shard_open",
              "data.prefetch_wait")


def _render_io_pipeline(doc, out):
    """Streaming-input digest: record/byte/torn counters, open-shard
    gauge, and the io.* phase table — so "is the input plane keeping
    up, and what is it costing" reads off one report the way the
    checkpoint pipeline does."""
    c = doc.get("counters") or {}
    phases = doc.get("phases") or {}
    records = c.get("io.records", 0)
    if not records and not any(
            (phases.get(k) or {}).get("count") for k in _IO_PHASES[:3]):
        return
    g = doc.get("gauges") or {}
    out.write("\n  stream input plane: records=%d bytes=%d torn=%d "
              "batches=%d shards_open=%s\n"
              % (records, c.get("io.bytes", 0),
                 c.get("io.torn_records", 0), c.get("data.batches", 0),
                 g.get("io.shards_open", "-")))
    rows = []
    for name in _IO_PHASES:
        h = phases.get(name)
        if not h or not h["count"]:
            continue
        rows.append((name, h["count"], _fmt_s(h["sum"] / h["count"]),
                     _fmt_s(h["p50"]), _fmt_s(h["p99"]),
                     _fmt_s(h["max"]), _fmt_s(h["sum"])))
    _table(("span", "count", "mean", "p50", "p99", "max", "total"),
           rows, out)


def _render_serving_plane(doc, out):
    """Serving-scope digest (OBSERVABILITY.md §12): the request/token/
    goodput counters and — when the line carries the periodic serving
    status block — one row per live engine (occupancy, pages, SLO
    controller state, weights epoch).  ``serve_report.py`` (same
    directory) merges the whole fleet; this renders one process's
    view faithfully."""
    c = doc.get("counters") or {}
    serving = doc.get("serving") or []
    requests = c.get("serving.requests", 0)
    if not requests and not serving and not c.get("router.requests"):
        return
    tokens = c.get("serving.tokens", 0)
    goodput = c.get("serving.goodput", 0)
    out.write("\n  serving plane: requests=%d tokens=%d goodput=%d "
              "(%.1f%%) shed=%d expired=%d+%d swaps=%d rollbacks=%d "
              "trace_dropped=%d\n"
              % (requests, tokens, goodput,
                 100.0 * goodput / tokens if tokens else 100.0,
                 c.get("serving.shed", 0),
                 c.get("serving.expired_queue", 0),
                 c.get("serving.expired_decode", 0),
                 c.get("serving.swaps", 0),
                 c.get("serving.swap_rollbacks", 0),
                 c.get("serving.trace_dropped", 0)))
    rows = []
    for s in serving:
        slo = s.get("slo") or {}
        rows.append((s.get("replica"), "%s/%s" % (s.get("occupancy"),
                                                  s.get("num_slots")),
                     s.get("queued"),
                     "%s/%s" % (s.get("free_pages"), s.get("num_pages")),
                     s.get("decode_steps"),
                     "drain" if s.get("draining") else
                     ("shed" if s.get("shedding") else "ok"),
                     ("-" if slo.get("windowed_p99_s") is None
                      else _fmt_s(slo.get("windowed_p99_s"))),
                     s.get("weights_epoch")
                     if s.get("weights_epoch") is not None else "-"))
    if rows:
        _table(("engine", "occ", "queued", "pages_free", "steps",
                "state", "slo_p99", "epoch"), rows, out)


def render_router_journal(docs, out, path=""):
    """Summarize a Router audit journal (one JSON line per lifecycle
    transition): event counts, failover arcs, terminal verdicts — the
    faithful single-artifact view; ``serve_report.py`` joins it with
    the replica streams for blame."""
    events = {}
    verdicts = {}
    retries = [d for d in docs if d.get("event") == "retry"]
    for d in docs:
        events[d.get("event", "?")] = events.get(d.get("event", "?"),
                                                 0) + 1
        if d.get("event") in ("complete", "fail", "refuse", "drop",
                              "reject") and d.get("verdict"):
            verdicts[d["verdict"]] = verdicts.get(d["verdict"], 0) + 1
    out.write("== ROUTER JOURNAL%s: %d line(s), %d request(s) ==\n"
              % ((" " + path) if path else "", len(docs),
                 len({d.get("rid") for d in docs})))
    out.write("  events: " + "  ".join(
        "%s=%d" % kv for kv in sorted(events.items())) + "\n")
    if verdicts:
        out.write("  terminal verdicts: " + "  ".join(
            "%s=%d" % kv for kv in sorted(verdicts.items())) + "\n")
    for d in retries:
        out.write("  failover: rid %s trace %s off replica %s "
                  "(retry %s)\n"
                  % (d.get("rid"), d.get("trace"), d.get("from_replica"),
                     d.get("retries")))


def render_membership(doc, out):
    """The elastic membership journal as a timeline: one row per
    transition, so "what did the job's world look like over time" reads
    straight down (the launcher-side sibling of the in-worker
    ``elastic.*`` metrics)."""
    trans = doc.get("transitions") or []
    n_evict = sum(1 for t in trans if t.get("event") == "evict")
    n_readmit = sum(1 for t in trans if t.get("event") == "readmit")
    out.write("== MEMBERSHIP: %d slot(s), %d transition(s), %d "
              "eviction(s), %d re-admission(s) ==\n"
              % (doc.get("total_slots", 0), len(trans), n_evict,
                 n_readmit))
    t0 = trans[0].get("time", 0) if trans else 0
    rows = []
    for t in trans:
        event = t.get("event", "?")
        detail = ""
        if event == "failure":
            detail = "slot %s rank %s rc=%s %s" % (
                t.get("slot"), t.get("rank"), t.get("rc"),
                t.get("kind", ""))
        elif event in ("evict", "readmit"):
            detail = "slot %s%s" % (
                t.get("slot"),
                (": " + t["reason"]) if t.get("reason") else "")
        elif event == "attempt_start":
            detail = "port %s" % t.get("port")
        rows.append(("+" + _fmt_s(t.get("time", 0) - t0),
                     t.get("attempt"), event, t.get("world_size"),
                     ",".join(str(s) for s in
                              t.get("active_slots", [])) or "-",
                     ",".join(str(s) for s in
                              t.get("evicted_slots", [])) or "-",
                     detail))
    _table(("when", "attempt", "event", "world", "active", "evicted",
            "detail"), rows, out)


def render_postmortem(doc, out):
    """Pretty-print a flight-recorder crash postmortem."""
    out.write("== POSTMORTEM (pid %s)%s ==\n"
              % (doc.get("pid"), _identity_line(doc)))
    out.write("  reason: %s\n" % doc.get("reason"))
    mem = doc.get("membership") or {}
    if mem.get("coordinator") or (mem.get("world_size") or 1) > 1 or \
            mem.get("transitions"):
        out.write("  membership: world_size=%s rank=%s slot=%s "
                  "attempt=%s transitions=%s\n"
                  % (mem.get("world_size"), mem.get("rank"),
                     mem.get("slot"), mem.get("attempt"),
                     mem.get("transitions")))
    ss = doc.get("step_stats") or {}
    out.write("  step_stats: %s\n" % json.dumps(ss))
    wd = doc.get("watchdog") or {}
    if wd.get("leases") or str(doc.get("reason", "")).startswith("stall"):
        prog = wd.get("progress") or {}
        out.write("  watchdog: armed=%s timeout=%ss grace=%ss "
                  "last-progress step=%s phase=%s\n"
                  % (wd.get("armed"), wd.get("timeout"), wd.get("grace"),
                     prog.get("step"), prog.get("phase")))
        rows = [(name, _fmt_s(lease.get("age_s")),
                 _fmt_s(lease.get("timeout_s")), lease.get("step"))
                for name, lease in sorted((wd.get("leases") or {}).items())]
        _table(("lease", "age", "timeout", "step"), rows, out)
    fires = doc.get("fault_fires") or {}
    if fires:
        out.write("  fault firings: " + "  ".join(
            "%s x%d" % kv for kv in sorted(fires.items())) + "\n")
    steps = doc.get("last_steps") or []
    out.write("\n  last %d step records (flight recorder, ring %s):\n"
              % (len(steps), (doc.get("flight") or {}).get("maxlen")))
    rows = []
    for r in steps[-20:]:
        rows.append((r["step"],
                     _fmt_s(r["dispatch_s"]), _fmt_s(r["sync_s"]),
                     r["dispatch_delta"], r["compile_delta"],
                     "SKIP" if r["skipped"] else
                     ("?" if r["skipped"] is None else "ok"),
                     "-" if r["loss"] is None else "%.4g" % r["loss"],
                     ",".join(r["faults"]) or "-"))
    _table(("step", "dispatch", "sync", "disp+", "comp+", "guard",
            "loss", "faults"), rows, out)
    if len(steps) > 20:
        out.write("  (%d older records omitted)\n" % (len(steps) - 20))
    render_report(doc, out, context=" (at crash)")


def parse_artifact(path, notes=None):
    """Parse one telemetry artifact file → list of JSON docs (one for a
    postmortem/journal, one per line for an emitter stream).  Torn lines
    (a process killed mid-append — the exact crash this tooling serves)
    are skipped and counted into ``notes`` (a list of strings)."""
    with open(path) as f:
        text = f.read()
    if not text.strip():
        return []
    try:
        # a postmortem is one (indented, multi-line) JSON document
        return [json.loads(text)]
    except ValueError:
        docs, skipped = [], 0
        for ln in text.splitlines():
            if not ln.strip():
                continue
            try:
                docs.append(json.loads(ln))
            except ValueError:
                skipped += 1
        if skipped and notes is not None:
            notes.append("(%d unparseable line(s) skipped in %s — torn "
                         "mid-append write)" % (skipped, path))
        return docs


def render_file(path, out=sys.stdout):
    notes = []
    docs = parse_artifact(path, notes)
    for note in notes:
        out.write("  %s\n" % note)
    if not docs:
        out.write("%s: %s\n" % (path, "empty" if not notes
                                else "no parseable JSON"))
        return
    last = docs[-1]
    schema = str(last.get("schema") or "")
    if schema.startswith("mxtpu-postmortem-"):
        render_postmortem(last, out)
        return
    if schema.startswith("mxtpu-membership-"):
        render_membership(last, out)
        return
    if not schema and "rid" in last and "event" in last:
        # a Router audit journal: schema-less JSON lines keyed by
        # request id + lifecycle event
        render_router_journal(docs, out)
        return
    ctx = ""
    if len(docs) > 1:
        span = last.get("time_unix", 0) - docs[0].get("time_unix", 0)
        ctx = " (%d samples over %s)" % (len(docs), _fmt_s(span))
    _render_watchdog_timeline(docs, out)
    _render_alert_timeline(docs, out)
    render_report(last, out, context=ctx)


def discover_run_dir(run_dir):
    """Inventory a launch.py run dir: the membership journal, every
    per-slot stream, every router journal (the serving fleet's audit
    record — ``router-journal*.jsonl``, the ``MXTPU_SERVE_JOURNAL``
    layout), every postmortem, every stall-stacks dump — looking both at
    the top level and under ``telemetry/`` (the launcher's default
    tree).  Returns ``{"membership": path|None, "streams": [...],
    "router_journals": [...], "postmortems": [...],
    "stall_stacks": [...]}`` with sorted lists.  Shared with
    job_report.py and serve_report.py (their input contract)."""
    roots = [run_dir, os.path.join(run_dir, "telemetry")]
    found = {"membership": None, "streams": [], "router_journals": [],
             "postmortems": [], "stall_stacks": []}
    for root in roots:
        try:
            names = sorted(os.listdir(root))
        except OSError:
            continue
        for name in names:
            path = os.path.join(root, name)
            if not os.path.isfile(path):
                continue
            if name == "membership.json":
                found["membership"] = found["membership"] or path
            elif name.startswith("router-journal") and \
                    name.endswith(".jsonl"):
                found["router_journals"].append(path)
            elif name.endswith(".jsonl"):
                found["streams"].append(path)
            elif name.startswith("postmortem-") and \
                    name.endswith(".json"):
                found["postmortems"].append(path)
            elif name.startswith("stall-stacks-"):
                found["stall_stacks"].append(path)
    return found


def render_run_dir(run_dir, out=sys.stdout):
    """Render every artifact of one run dir, membership journal first
    (the job's shape over time), then each rank stream, then each
    postmortem, with a stall-stacks inventory line at the end."""
    found = discover_run_dir(run_dir)
    if not (found["membership"] or found["streams"]
            or found["router_journals"] or found["postmortems"]):
        out.write("%s: no telemetry artifacts (membership.json, "
                  "*.jsonl, postmortem-*.json)\n" % run_dir)
        return
    out.write("== RUN DIR %s ==\n" % run_dir)
    first = True
    for path in ([found["membership"]] if found["membership"] else []) \
            + found["streams"] + found["router_journals"] \
            + found["postmortems"]:
        if not first:
            out.write("\n")
        first = False
        out.write("-- %s --\n" % os.path.relpath(path, run_dir))
        render_file(path, out)
    if found["stall_stacks"]:
        out.write("\n  stall-stacks dumps: %s\n" % ", ".join(
            os.path.relpath(p, run_dir) for p in found["stall_stacks"]))
    if found["router_journals"]:
        out.write("\n  serving artifacts present: serve_report.py "
                  "(same directory) merges the router journal with the "
                  "replica streams into the fleet view (request "
                  "lifecycles, failover arcs, SLO breach blame)\n")


def _render_watchdog_timeline(docs, out):
    """Call out hang-defense events across an emitter timeline: the
    samples where ``watchdog.stalls`` incremented (with the worst lease
    age the sample carried), so a soak run's stalls are visible without
    diffing counters by hand."""
    t0 = docs[0].get("time_unix", 0)
    prev = 0
    events = []
    for doc in docs:
        v = (doc.get("counters") or {}).get("watchdog.stalls", 0) or 0
        if v > prev:
            events.append((doc.get("time_unix", 0) - t0, v - prev,
                           (doc.get("gauges") or {})
                           .get("watchdog.lease_age")))
        prev = v
    if not events:
        return
    out.write("== WATCHDOG: %d stall(s) in this timeline ==\n"
              % sum(n for _, n, _ in events))
    for t, n, age in events:
        out.write("  +%s: %d stall(s) detected (lease_age %s)\n"
                  % (_fmt_s(t), n, _fmt_s(age) if age is not None
                     else "-"))


def _render_alert_timeline(docs, out):
    """Call out the alert-rule firings (ISSUE 18) riding a stream as
    trace-less ``alert`` request events, so a timeline's rule verdicts
    (breaker opened, watchdog stalled, goodput collapsed, ...) read at
    the top without grepping req_events by hand."""
    t0 = docs[0].get("time_unix", 0)
    fired = []
    for doc in docs:
        for e in doc.get("req_events") or []:
            if e.get("event") == "alert":
                fired.append((e.get("t", 0) - t0, e.get("args") or {}))
    if not fired:
        return
    out.write("== ALERTS: %d rule firing(s) in this timeline ==\n"
              % len(fired))
    for t, a in sorted(fired):
        out.write("  +%s: [%s] %s (%s=%s)\n"
                  % (_fmt_s(max(0.0, t)), a.get("severity", "?"),
                     a.get("rule", "?"), a.get("metric", "?"),
                     a.get("value", "-")))


def main(argv):
    if not argv:
        sys.stderr.write(__doc__)
        return 2
    for i, path in enumerate(argv):
        if i:
            sys.stdout.write("\n")
        if os.path.isdir(path):
            render_run_dir(path)
        else:
            render_file(path)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
