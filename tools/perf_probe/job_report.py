"""Job-scope telemetry aggregator: one report for an N-rank run.

A ``tools/launch.py --run-dir`` job leaves one telemetry tree behind
(``<run-dir>/telemetry/`` next to ``membership.json``): per-slot
JSON-lines streams (schema ``mxtpu-telemetry-2`` — every line carries a
rank/slot/attempt/world identity block and a monotonic↔unix clock
anchor), crash postmortems, and stall-stacks dumps.
``telemetry_report.py`` renders each artifact faithfully; THIS tool
answers the job-level questions none of them can alone:

- **who is slow, and why** — a per-rank matrix (steps, step-time EMA,
  ``fit_step.dispatch``/``fit_step.sync``/``data.prefetch_wait`` p50s,
  guard skips, recompiles) per attempt segment, with **straggler blame**:
  a rank whose ``fit_step.dispatch + fit_step.sync`` p50 exceeds the job
  median by ``--straggler-factor`` (default 2.0) is named, with the
  ratio.  **Input-stall blame** is detected and rendered DISTINCTLY
  (``INPUT-STALL`` vs ``STRAGGLER``): a rank data-starved on its
  prefetch/decode queues (``data.prefetch_wait + io.queue_wait`` p50,
  same leave-one-out law) is an input-pipeline problem, not a compute
  one, and ranks that streamed get an io.* table (records/bytes/torn,
  decode + queue-wait p50s).  The ``step.slow`` / ``data.slow`` /
  ``io.decode.slow`` fault sites (``MXTPU_FAULT_SLOTS`` scopes them to
  one victim rank) make both detectors drillable end-to-end.
- **one merged trace** — every rank's recent per-step spans (the flight
  ring each rank leaves in its stream's final line, or in its postmortem
  when it crashed) rendered into a single Perfetto/chrome-tracing file
  on the common unix clock: one process row per SLOT (elastic-stable),
  one thread row per attempt, membership transitions as instant events
  on a ``job`` track (``--trace-out``).
- **the job's shape over time** — the timeline is segmented at elastic
  transitions: each attempt renders as its own section with its world
  size, the membership events that ended it, and its own rank matrix —
  so "rank 1 was slow in attempt 0, evicted before attempt 1" reads
  straight down.

Usage:
    python tools/perf_probe/job_report.py RUN_DIR \
        [--straggler-factor 2.0] [--trace-out job-trace.json]

OBSERVABILITY.md §8 is the schema/threshold contract.
"""
import argparse
import json
import os
import sys
from statistics import median

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import telemetry_report as _tr  # noqa: E402 (sibling module)

#: synthetic chrome-trace pid for the job-level (membership) track —
#: real tracks use the worker slot as pid, which is always small
JOB_TRACK_PID = 999999


def _identity(doc):
    ident = doc.get("identity") or {}
    return {
        "rank": ident.get("rank"),
        "slot": ident.get("slot"),
        "attempt": ident.get("attempt") or 0,
        "world_size": ident.get("world_size"),
        "pid": ident.get("pid") or doc.get("pid"),
    }


def _slot_from_path(path):
    """Fallback identity for schema-1 lines: the launcher names streams
    ``stream-slot<K>.jsonl``."""
    base = os.path.basename(path)
    if base.startswith("stream-slot"):
        digits = base[len("stream-slot"):].split(".")[0]
        if digits.isdigit():
            return int(digits)
    return None


def load_job(run_dir):
    """Parse every artifact of the run dir into one structure:
    ``streams`` — every stream line tagged with its identity (falling
    back to the per-slot filename), ``postmortems`` — parsed docs,
    ``membership`` — the journal doc or None, plus parser notes (torn
    lines)."""
    found = _tr.discover_run_dir(run_dir)
    notes = []
    membership = None
    if found["membership"]:
        docs = _tr.parse_artifact(found["membership"], notes)
        membership = docs[-1] if docs else None
    streams = []
    for path in found["streams"]:
        slot = _slot_from_path(path)
        for doc in _tr.parse_artifact(path, notes):
            ident = _identity(doc)
            if ident["slot"] is None:
                ident["slot"] = slot
            if ident["rank"] is None:
                ident["rank"] = slot
            doc["_ident"] = ident
            doc["_path"] = path
            streams.append(doc)
    postmortems = []
    for path in found["postmortems"]:
        docs = _tr.parse_artifact(path, notes)
        if docs:
            doc = docs[-1]
            doc["_ident"] = _identity(doc)
            doc["_path"] = path
            postmortems.append(doc)
    return {"run_dir": run_dir, "membership": membership,
            "streams": streams, "postmortems": postmortems,
            "stall_stacks": found["stall_stacks"], "notes": notes}


def group_attempts(job):
    """{attempt: {rank: [stream docs, time-ordered]}} — the segmented
    view.  Each attempt is a fresh set of worker processes, so the
    cumulative counters inside one (attempt, rank) group restart from
    zero at the group's first line."""
    attempts = {}
    for doc in job["streams"]:
        ident = doc["_ident"]
        rank = ident["rank"] if ident["rank"] is not None else -1
        attempts.setdefault(ident["attempt"], {}) \
            .setdefault(rank, []).append(doc)
    for ranks in attempts.values():
        for docs in ranks.values():
            docs.sort(key=lambda d: d.get("time_unix", 0))
    return attempts


def _phase_p50(doc, name):
    h = (doc.get("phases") or {}).get(name)
    return h.get("p50") if h and h.get("count") else None


def rank_rows(ranks):
    """Per-rank summary rows for one attempt segment, from each rank's
    LAST line (cumulative within the attempt's process lifetime).
    Returns ``[{rank, slot, world, steps, ema_s, dispatch_p50, sync_p50,
    data_wait_p50, io_wait_p50, io_records, io_torn, skipped, compiles,
    score, input_score}]`` sorted by rank; ``score`` is the compute
    straggler-blame metric (dispatch+sync p50), ``input_score`` the
    input-stall one (prefetch starvation + decode-queue starvation)."""
    rows = []
    for rank in sorted(ranks):
        last = ranks[rank][-1]
        ident = last["_ident"]
        ss = last.get("step_stats") or {}
        c = last.get("counters") or {}
        dispatch = _phase_p50(last, "fit_step.dispatch")
        sync = _phase_p50(last, "fit_step.sync")
        score = None
        if dispatch is not None:
            score = dispatch + (sync or 0.0)
        data_wait = _phase_p50(last, "data.prefetch_wait")
        io_wait = _phase_p50(last, "io.queue_wait")
        input_score = None
        if data_wait is not None or io_wait is not None:
            input_score = (data_wait or 0.0) + (io_wait or 0.0)
        rows.append({
            "rank": rank, "slot": ident.get("slot"),
            "world": ident.get("world_size"),
            "steps": ss.get("steps"),
            "ema_s": ss.get("step_time_ema_s"),
            "dispatch_p50": dispatch, "sync_p50": sync,
            "data_wait_p50": data_wait,
            "io_wait_p50": io_wait,
            "io_decode_p50": _phase_p50(last, "io.decode"),
            "io_records": c.get("io.records"),
            "io_bytes": c.get("io.bytes"),
            "io_torn": c.get("io.torn_records"),
            "skipped": ss.get("skipped_steps"),
            "compiles": ss.get("compile_count"),
            "score": score,
            "input_score": input_score,
        })
    return rows


def find_stragglers(rows, factor):
    """Skew detection: ranks whose dispatch+sync p50 exceeds the job
    median by ``factor``.  Returns ``[(row, ratio)]``, worst first.

    The baseline for each candidate is the median of the OTHER ranks'
    scores (leave-one-out): a straggling minority cannot drag the
    baseline up to hide itself, and — decisive at world size 2 — a
    candidate's own score never caps its ratio (with scores [h, s] a
    plain median is (h+s)/2, so s/median < 2 for ANY slowdown and a
    2-rank job could never cross the default factor)."""
    scored = [r for r in rows if r["score"]]
    if len(scored) < 2:
        return []
    out = []
    for r in scored:
        baseline = median(o["score"] for o in scored if o is not r)
        if baseline > 0 and r["score"] > factor * baseline:
            out.append((r, r["score"] / baseline))
    return sorted(out, key=lambda p: -p[1])


def find_input_stalls(rows, factor):
    """Input-plane skew detection, same leave-one-out law as
    :func:`find_stragglers` but over the time a rank spends STARVED for
    data (``data.prefetch_wait`` + ``io.queue_wait`` p50s).  A rank can
    be blamed here and NOT in the compute detector — a stalled input
    pipeline hides inside dispatch gaps, not inside the dispatch span —
    which is exactly why the two blames render distinctly."""
    scored = [r for r in rows if r["input_score"]]
    if len(scored) < 2:
        return []
    out = []
    for r in scored:
        baseline = median(o["input_score"] for o in scored if o is not r)
        if baseline > 0 and r["input_score"] > factor * baseline:
            out.append((r, r["input_score"] / baseline))
    return sorted(out, key=lambda p: -p[1])


def _flight_sources(job):
    """Every (ident, last_steps) span source in the job: each stream
    line that carries the flight ring (final lines; one per attempt per
    rank) and each postmortem (a crashed rank's equivalent record).

    Deduplicated per (slot, attempt, pid): a rank that dies on an
    uncaught exception leaves the SAME ring twice — in its excepthook
    postmortem and in its atexit final stream line — and without the
    dedup every span of that process would render twice on its track.
    The fuller record wins (the later dump may hold more steps)."""
    best = {}
    order = []
    for doc in job["streams"] + job["postmortems"]:
        recs = doc.get("last_steps")
        if not recs:
            continue
        ident = doc["_ident"]
        key = (ident.get("slot"), ident.get("attempt"),
               ident.get("pid"))
        cur = best.get(key)
        if cur is None:
            order.append(key)
        if cur is None or len(recs) > len(cur[1]):
            best[key] = (ident, recs)
    return [best[k] for k in order]


def merged_trace(job):
    """One chrome-tracing document for the whole job on the common unix
    clock: per-step ``fit_step.dispatch``/``fit_step.sync`` spans from
    every rank's flight records (pid = slot, tid = attempt — slots are
    elastic-stable, so a re-ranked survivor keeps its track), plus the
    membership journal's transitions as instant events on a ``job``
    track.  Returns ``(doc, t0_unix)``; t0 is the earliest stamp so
    Perfetto's axis starts at ~0."""
    sources = _flight_sources(job)
    stamps = [rec["t_unix"] for _, recs in sources for rec in recs]
    trans = (job["membership"] or {}).get("transitions") or []
    stamps += [t.get("time", 0) for t in trans]
    t0 = min(stamps) if stamps else 0.0
    events = [{"ph": "M", "name": "process_name", "pid": JOB_TRACK_PID,
               "args": {"name": "job (membership)"}}]
    seen_tracks = set()
    for ident, recs in sources:
        slot = ident.get("slot") if ident.get("slot") is not None else -1
        attempt = ident.get("attempt") or 0
        if slot not in seen_tracks:
            seen_tracks.add(slot)
            events.append({"ph": "M", "name": "process_name",
                           "pid": slot,
                           "args": {"name": "slot %s" % slot}})
        events.append({"ph": "M", "name": "thread_name", "pid": slot,
                       "tid": attempt,
                       "args": {"name": "attempt %d (rank %s, world %s)"
                                % (attempt, ident.get("rank"),
                                   ident.get("world_size"))}})
        for rec in recs:
            ts = (rec["t_unix"] - t0) * 1e6
            dur = (rec.get("dispatch_s") or 0.0) * 1e6
            args = {"step": rec.get("step")}
            if rec.get("skipped"):
                args["skipped"] = True
            if rec.get("loss") is not None:
                args["loss"] = rec["loss"]
            if rec.get("faults"):
                args["faults"] = list(rec["faults"])
            # flight records carry their origin since schema grew the
            # `where` field (serve_step / serve_prefill / fit_step);
            # older artifacts default to the training name
            where = rec.get("where") or "fit_step"
            events.append({"name": where + ".dispatch", "cat": "step",
                           "ph": "X", "pid": slot, "tid": attempt,
                           "ts": ts, "dur": dur, "args": args})
            if rec.get("sync_s") is not None:
                events.append({"name": where + ".sync", "cat": "step",
                               "ph": "X", "pid": slot, "tid": attempt,
                               "ts": ts + dur,
                               "dur": rec["sync_s"] * 1e6,
                               "args": {"step": rec.get("step")}})
    for t in trans:
        name = t.get("event", "?")
        if name in ("failure", "evict", "readmit"):
            name = "%s slot %s" % (name, t.get("slot"))
        elif name == "attempt_start":
            name = "attempt %s start (world %s)" % (t.get("attempt"),
                                                    t.get("world_size"))
        events.append({"name": name, "cat": "membership", "ph": "i",
                       "s": "g", "pid": JOB_TRACK_PID, "tid": 0,
                       "ts": (t.get("time", 0) - t0) * 1e6,
                       "args": {k: v for k, v in t.items()
                                if k != "time"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}, t0


def _fmt(v, fmt="%s"):
    return "-" if v is None else fmt % v


def render(job, out, factor=2.0):
    """The job report: membership summary, then one section per attempt
    (world size, membership events, rank matrix, straggler verdict),
    then the crash/stall inventory."""
    attempts = group_attempts(job)
    trans = (job["membership"] or {}).get("transitions") or []
    n_ranks = {ident for doc in job["streams"]
               for ident in [(doc["_ident"]["attempt"],
                              doc["_ident"]["rank"])]}
    out.write("== JOB REPORT %s ==\n" % job["run_dir"])
    out.write("  %d stream line(s) from %d (attempt, rank) pair(s); "
              "%d attempt segment(s); %d postmortem(s); %d stall-stack "
              "dump(s)\n"
              % (len(job["streams"]), len(n_ranks), len(attempts),
                 len(job["postmortems"]), len(job["stall_stacks"])))
    for note in job["notes"]:
        out.write("  %s\n" % note)
    all_stragglers = []
    for attempt in sorted(attempts):
        ranks = attempts[attempt]
        rows = rank_rows(ranks)
        world = next((r["world"] for r in rows
                      if r["world"] is not None), len(rows))
        t_lo = min(d.get("time_unix", 0) for docs in ranks.values()
                   for d in docs)
        t_hi = max(d.get("time_unix", 0) for docs in ranks.values()
                   for d in docs)
        out.write("\n-- attempt %d (world size %s, %s observed) --\n"
                  % (attempt, world, _tr._fmt_s(t_hi - t_lo)))
        for t in trans:
            if t.get("attempt") == attempt and \
                    t.get("event") not in ("attempt_start", "launch"):
                detail = ""
                if t.get("slot") is not None:
                    detail = " slot %s" % t.get("slot")
                    if t.get("rc") is not None:
                        detail += " (rc=%s)" % t.get("rc")
                out.write("  membership: %s%s\n"
                          % (t.get("event"), detail))
        table = [(r["rank"], r["slot"], _fmt(r["steps"]),
                  _tr._fmt_s(r["ema_s"]),
                  _tr._fmt_s(r["dispatch_p50"]),
                  _tr._fmt_s(r["sync_p50"]),
                  _tr._fmt_s(r["data_wait_p50"]),
                  _fmt(r["skipped"]), _fmt(r["compiles"]))
                 for r in rows]
        _tr._table(("rank", "slot", "steps", "step_ema", "disp_p50",
                    "sync_p50", "data_wait", "skipped", "compiles"),
                   table, out)
        if any(r["io_records"] or r["io_torn"] for r in rows):
            # the streaming input plane, one row per rank (any rank
            # that streamed — INCLUDING one whose records were all
            # torn: hiding the torn counter would be exactly the
            # silent cap it exists to prevent)
            io_table = [(r["rank"], r["slot"], _fmt(r["io_records"]),
                         _fmt(r["io_bytes"]), _fmt(r["io_torn"] or 0),
                         _tr._fmt_s(r["io_decode_p50"]),
                         _tr._fmt_s(r["io_wait_p50"]),
                         _tr._fmt_s(r["data_wait_p50"]))
                        for r in rows
                        if r["io_records"] or r["io_torn"]]
            out.write("  stream input plane (io.*):\n")
            _tr._table(("rank", "slot", "records", "bytes", "torn",
                        "decode_p50", "ioq_wait", "data_wait"),
                       io_table, out)
        stragglers = find_stragglers(rows, factor)
        input_stalls = find_input_stalls(rows, factor)
        stalled_ranks = {row["rank"] for row, _ in input_stalls}
        for row, ratio in stragglers:
            note = ""
            if row["rank"] in stalled_ranks:
                note = " [also input-stalled — see INPUT-STALL below]"
            out.write("  STRAGGLER: rank %s (slot %s) — "
                      "dispatch+sync p50 %s is %.1fx the other ranks' "
                      "median (threshold %.1fx)%s\n"
                      % (row["rank"], row["slot"],
                         _tr._fmt_s(row["score"]), ratio, factor, note))
            all_stragglers.append((attempt, row, ratio))
        for row, ratio in input_stalls:
            # input stalls are blamed DISTINCTLY from compute
            # stragglers: the victim's steps are starved, not slow
            out.write("  INPUT-STALL: rank %s (slot %s) — data-starved "
                      "%s per batch (prefetch+decode-queue wait p50), "
                      "%.1fx the other ranks' median — input pipeline, "
                      "not compute\n"
                      % (row["rank"], row["slot"],
                         _tr._fmt_s(row["input_score"]), ratio))
            all_stragglers.append((attempt, row, ratio))
        if len(rows) >= 2 and not stragglers and not input_stalls:
            out.write("  no straggler: every rank within %.1fx of the "
                      "other ranks' median dispatch+sync p50 and "
                      "data-wait p50\n" % factor)
    for doc in job["postmortems"]:
        ident = doc["_ident"]
        out.write("\n  postmortem: rank %s slot %s attempt %s — %s\n"
                  % (ident.get("rank"), ident.get("slot"),
                     ident.get("attempt"),
                     str(doc.get("reason", ""))[:120]))
    return all_stragglers


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Merge an N-rank run's telemetry into one job "
        "report: per-rank matrix, straggler blame, merged chrome trace")
    ap.add_argument("run_dir", help="tools/launch.py --run-dir (holds "
                    "membership.json and the telemetry/ tree)")
    ap.add_argument("--straggler-factor", type=float, default=2.0,
                    help="blame a rank when its fit_step dispatch+sync "
                    "p50 exceeds the job median by this factor "
                    "(default 2.0)")
    ap.add_argument("--trace-out", default=None,
                    help="also write the merged cross-rank chrome trace "
                    "(Perfetto-loadable) to this path")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        sys.stderr.write("job_report.py: %s is not a run dir\n"
                         % args.run_dir)
        return 2
    job = load_job(args.run_dir)
    if not job["streams"] and not job["postmortems"]:
        sys.stderr.write("job_report.py: no telemetry streams or "
                         "postmortems under %s (launch with --run-dir/"
                         "--telemetry-dir?)\n" % args.run_dir)
        return 1
    render(job, sys.stdout, factor=args.straggler_factor)
    if args.trace_out:
        doc, t0 = merged_trace(job)
        with open(args.trace_out, "w") as f:
            json.dump(doc, f)
        n_spans = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        sys.stdout.write("\n  merged trace: %s (%d span(s) across %d "
                         "track(s), t0=%.3f)\n"
                         % (args.trace_out, n_spans,
                            len({e["pid"] for e in doc["traceEvents"]
                                 if e["ph"] == "X"}), t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
