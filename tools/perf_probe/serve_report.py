"""Fleet serving report: request lifecycles, failover arcs, SLO blame.

The serving twin of ``job_report.py`` (ISSUE 13, OBSERVABILITY.md §12).
A serving fleet leaves three artifact kinds behind in one run-dir tree
(``tools/launch.py --run-dir`` / ``MXTPU_SERVE_JOURNAL`` layout): the
Router's audit journal (``router-journal*.jsonl``), each replica
process's telemetry stream (``stream-slot*.jsonl`` — every line carries
the request-trace events recorded since the previous line, plus the
periodic serving status block), and crash postmortems (which carry the
request-event ring).  ``telemetry_report.py`` renders each artifact
faithfully; THIS tool answers the fleet-level questions none can alone:

- **what did each request experience** — per-trace lifecycle
  reconstruction (submit → admit → prefill → every decode token → one
  terminal verdict), across replicas: a failed-over request's victim
  and survivor segments are ONE trace linked by the Router's ``retry``
  event, so the arc reads as a single story;
- **who served what, and how well** — a per-replica request matrix
  (admits, tokens, verdicts, retries-out) and TTFT / TPOT / queue-wait
  percentiles SPLIT BY VERDICT CLASS (a p99 that mixes completed and
  shed requests describes nothing);
- **who was suspected, who was confirmed dead, and why** — a
  per-replica liveness lane (ISSUE 17): suspicion spans from the RPC
  heartbeat view, the worst observed heartbeat gap, the typed
  confirmation reason (incarnation / kill_ack / fence_expiry) named on
  each failover arc, and fenced late-completion rejections;
- **SLO breach blame** — every deadline-missed / shed / failed-over
  (and, with ``--slo-ttft``, p99-breaching) request decomposed into its
  phase budget: queue wait, prefill, decode, hot-swap pauses, failover
  re-decode — with the dominant phase and the responsible replica
  named.  "Replica a died and its victims spent 60% of their budget
  re-decoding on b" is a sentence this tool prints, not a forensic
  project.  Streamed requests (ISSUE 19) add a **delivery** phase:
  the poll-gap windows between each token's emit and the first
  successful poll that covered it — a slow poller is the client's
  latency, never blamed on the replica's decode;
- **streamed vs unary TTFT** (ISSUE 19) — first-token percentiles
  split by delivery mode: the streamed class measures submit → first
  token DELIVERED through ``poll``, the unary class measures the
  engine's emit stamp and its completion (the whole point of
  streaming is that the first number beats the last one);
- **goodput and cost-per-token** — ``serving.goodput`` (tokens on
  requests that completed within deadline) vs raw ``serving.tokens``,
  joined with the compile-time ``serving.cost.*`` attribution of the
  decode/prefill executables into measured flops-and-bytes-per-token —
  the objective function the ROADMAP-item-2 autotuner optimizes;
- **one merged chrome trace** (``--trace-out``) — pid = replica,
  tid = decode slot, one span per residency segment, token instants,
  flow arrows linking failover arcs across replicas, hot-swap pauses,
  and each process's recent decode-step spans — loadable as ONE file
  in Perfetto.

Torn artifact lines (a process killed mid-append) are skipped and
counted, and request events evicted before any stream line could carry
them are declared per line (``req_dropped``) — no silent caps anywhere.

Usage:
    python tools/perf_probe/serve_report.py RUN_DIR \
        [--trace-out serve-trace.json] [--slo-ttft SECONDS]

``discover_run_dir`` / ``parse_artifact`` are shared with
``telemetry_report.py`` (one input contract, not two copies).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import telemetry_report as _tr  # noqa: E402 (sibling module)
from restart_probe import _pct  # noqa: E402 — shared percentile helper

#: verdicts that are refusals (the request never held a slot here)
REFUSAL_VERDICTS = ("shed", "draining", "no_live_replicas")
#: trace pid for per-process decode-step tracks (real replica pids are
#: small ordinals; keep the synthetic ones far away)
PROC_TRACK_BASE = 900
SWAP_TID = 9990


# -- loading ---------------------------------------------------------------

def load_serve(run_dir):
    """Parse the run dir into the fleet structure: request events (from
    every stream line's ``req_events`` and every postmortem's
    ``request_trace``, deduplicated by (process, seq) — a crashed
    replica leaves the SAME ring twice), the router journal, the last
    serving status block and counter snapshot per process, and each
    process's flight records (decode-step spans)."""
    found = _tr.discover_run_dir(run_dir)
    notes = []
    events = {}          # (proc key, seq) -> event dict (+"_pid")
    counters = {}        # proc key -> merged counters dict
    status = {}          # (proc key, engine tag) -> engine snapshot
    flights = {}         # proc key -> {step: flight rec}
    req_dropped = 0
    journal = []

    def _proc_key(doc):
        """One key per fleet PROCESS: identity slot + attempt + pid.
        The dedup's job is to match a process's stream lines against
        its own postmortem ring — but pid ALONE collides across
        containerized replicas (every container's service can be pid
        7) and across restart attempts that recycle a pid, and a
        collision would silently discard a whole replica's lifecycle
        record.  Slot/attempt (the elastic identity the transport
        stamps on every line) disambiguate both."""
        ident = doc.get("identity") or {}
        return (ident.get("slot"), ident.get("attempt"),
                ident.get("pid") or doc.get("pid"))

    def _fold(doc, recs):
        pkey = _proc_key(doc)
        pid = pkey[-1]
        for e in recs:
            events.setdefault((pkey, e.get("seq")), dict(e, _pid=pid))
        return pkey

    def _merge_counters(pkey, new):
        # counters are monotonic, so max-merge per process keeps
        # whichever artifact saw more — a process can leave SEVERAL
        # views of the same registry (its own emitter stream, the
        # ISSUE-18 pulled stream on the collector host, a postmortem),
        # and whichever file parses last must not roll the totals back
        cur = counters.setdefault(pkey, new)
        if cur is not new:
            for k, v in new.items():
                old = cur.get(k)
                if isinstance(v, (int, float)) and \
                        isinstance(old, (int, float)):
                    cur[k] = max(old, v)
                elif k not in cur:
                    cur[k] = v

    def _fold_flights(pkey, recs):
        # dedup by (process, step): pulled lines carry INCREMENTAL
        # flight slices, so one process's records arrive spread over
        # many lines (and possibly twice, via its own final line too)
        by_step = flights.setdefault(pkey, {})
        for rec in recs:
            by_step.setdefault(rec.get("step"), rec)

    for path in found["streams"]:
        for doc in _tr.parse_artifact(path, notes):
            pkey = _fold(doc, doc.get("req_events") or [])
            req_dropped += doc.get("req_dropped", 0)
            if doc.get("counters"):
                _merge_counters(pkey, doc["counters"])
            for snap in doc.get("serving") or []:
                status[(pkey, snap.get("replica"))] = snap
            if doc.get("last_steps"):
                _fold_flights(pkey, doc["last_steps"])
    for path in found["postmortems"]:
        docs = _tr.parse_artifact(path, notes)
        if docs:
            doc = docs[-1]
            pkey = _fold(doc, doc.get("request_trace") or [])
            _merge_counters(pkey, doc.get("counters") or {})
            for snap in doc.get("serving") or []:
                key = (pkey, snap.get("replica"))
                old = status.get(key)
                if old is None or (snap.get("decode_steps") or 0) >= \
                        (old.get("decode_steps") or 0):
                    status[key] = snap
    for path in found["router_journals"]:
        for doc in _tr.parse_artifact(path, notes):
            if "rid" in doc and "event" in doc:
                journal.append(doc)
    evs = sorted(events.values(),
                 key=lambda e: (e.get("t", 0), e.get("seq", 0)))
    flight_list = [(pk, [by_step[s] for s in sorted(
                        by_step, key=lambda s: (s is None, s))])
                   for pk, by_step in flights.items()]
    return {"run_dir": run_dir, "events": evs, "journal": journal,
            "counters": counters, "status": status,
            "flights": flight_list,
            "req_dropped": req_dropped, "notes": notes}


# -- lifecycle reconstruction ----------------------------------------------

def build_requests(events):
    """Per-trace lifecycle records from the merged event list.

    The batched ``tokens`` events (one per decode step, naming every
    advanced trace) are len-expanded here: each named trace gets one
    token at the step's stamp.  Engine-scope ``swap`` events are charged
    to the traces they name.  Returns ``{trace: record}`` where a record
    holds the ordered events, per-segment residency (a new segment per
    ``admit`` — a failover arc has one per replica), token timestamps,
    retries, swap pauses, and the final verdict.

    Ordering uses the merged-list POSITION (the (t, seq) sort of
    ``load_serve``), never raw ``seq``: seq counters are per-process,
    and a trace spanning a router process and a remote replica process
    would compare apples to oranges."""
    reqs = {}

    def rec(trace):
        r = reqs.get(trace)
        if r is None:
            r = reqs[trace] = {
                "trace": trace, "events": [], "segments": [],
                "token_ts": [], "retries": [], "swap_s": 0.0,
                "swap_count": 0, "verdicts": [], "final": None,
                "submit_t": None, "rid": None, "router": False,
                "prompt_len": None, "max_new": None,
                "deadline_s": None, "last_pos": -1,
                "prefix_hit": None, "prefix_len": None,
                "sampling": None, "poll_ts": [],
            }
        return r

    for pos, e in enumerate(events):
        ev, tr = e.get("event"), e.get("trace")
        args = e.get("args") or {}
        if ev == "tokens":
            for t in args.get("traces") or []:
                r = rec(t)
                r["token_ts"].append(e.get("t"))
                if r["segments"]:
                    r["segments"][-1]["tokens"] += 1
                r["last_pos"] = pos
            continue
        if ev == "swap":
            for t in args.get("traces") or []:
                r = rec(t)
                r["swap_s"] += args.get("dur_s") or 0.0
                r["swap_count"] += 1
            continue
        if ev == "poll":
            # delivery-plane event (ISSUE 19): trace-less like tokens/
            # swap — it feeds the delivery phase and the streamed-TTFT
            # split but NEVER the lifecycle record (a tail re-poll
            # after the final verdict is lawful, not a violation)
            t = args.get("trace")
            if t:
                rec(t)["poll_ts"].append((e.get("t"),
                                          args.get("cursor") or 0))
            continue
        if not tr:
            continue
        r = rec(tr)
        r["events"].append(e)
        r["last_pos"] = pos
        if ev == "submit":
            if r["submit_t"] is None:
                r["submit_t"] = e.get("t")
            r["router"] = r["router"] or bool(args.get("router"))
            for k in ("prompt_len", "max_new", "deadline_s"):
                if r[k] is None:
                    r[k] = args.get(k)
            if args.get("rid") is not None and r["router"]:
                r["rid"] = args.get("rid")
            if r["sampling"] is None:
                r["sampling"] = args.get("sampling")
        elif ev == "admit":
            r["segments"].append({
                "replica": args.get("replica"), "t": e.get("t"),
                "slot": args.get("slot"),
                "queue_wait_s": args.get("queue_wait_s") or 0.0,
                "prefill_s": 0.0, "tokens": 0, "end": None,
                "prefix_hit": args.get("prefix_hit"),
                "shared_pages": args.get("shared_pages"),
            })
            # the request's prefix class is its FIRST admission's (a
            # failover re-admission may hit where the original missed —
            # the class the caller FELT is the first one)
            if r["prefix_hit"] is None and \
                    args.get("prefix_hit") is not None:
                r["prefix_hit"] = bool(args.get("prefix_hit"))
                r["prefix_len"] = args.get("prefix_len")
        elif ev == "prefill":
            if r["segments"]:
                r["segments"][-1]["prefill_s"] += (
                    (args.get("dispatch_s") or 0.0)
                    + (args.get("sync_s") or 0.0))
        elif ev == "token":
            r["token_ts"].append(e.get("t"))
            if r["segments"]:
                r["segments"][-1]["tokens"] += 1
        elif ev == "retry":
            r["retries"].append({"t": e.get("t"),
                                 "from": args.get("from"),
                                 "reason": args.get("reason")})
            if r["segments"]:
                r["segments"][-1]["end"] = e.get("t")
        elif ev == "verdict":
            r["verdicts"].append(dict(e, _pos=pos))
            if args.get("final"):
                r["final"] = r["verdicts"][-1]
            if r["segments"] and r["segments"][-1]["end"] is None:
                r["segments"][-1]["end"] = e.get("t")
            if r["rid"] is None and args.get("rid") is not None:
                r["rid"] = args.get("rid")
    for r in reqs.values():
        _phase_budget(r)
    return reqs


def _phase_budget(r):
    """Decompose one request's wall time into its phase budget (the
    blame decomposition).  ``failover_s`` is the window from each
    ``retry`` until the survivor REGAINED the victim's progress (the
    k tokens produced before the loss exist again at overall token
    2k — greedy re-decode reproduces them bit-identically), so the
    re-decode is charged to the failover, not to useful decode.  The
    phases partition total wall time exactly: ``decode_s`` is the
    remainder, never double-counted."""
    final = r["final"] or (r["verdicts"][-1] if r["verdicts"] else None)
    t0 = r["submit_t"]
    t1 = final["t"] if final is not None else (
        r["token_ts"][-1] if r["token_ts"] else t0)
    if t0 is None or t1 is None:
        r["phases"] = None
        return
    total = max(0.0, t1 - t0)
    # a request that never reached a slot (expired in queue, shed,
    # refused) spent its WHOLE budget waiting — that is queue time,
    # not decode time
    queue = (sum(s["queue_wait_s"] for s in r["segments"])
             if r["segments"] else total)
    prefill = sum(s["prefill_s"] for s in r["segments"])
    swap = r["swap_s"]
    failover = 0.0
    ts = r["token_ts"]
    dup = 0   # tokens already re-produced by earlier failovers
    for ret in sorted(r["retries"], key=lambda x: x["t"] or 0):
        k = sum(1 for t in ts if t <= ret["t"])
        unique = k - dup      # the victim's NET progress to re-produce
        if unique <= 0:
            # killed while queued / pre-first-token: nothing to regain,
            # and the survivor's queue wait is already in queue_s —
            # charging a window here would double-count it
            continue
        target = k + unique   # overall token count at regained progress
        regained = ts[target - 1] if len(ts) >= target else t1
        failover += max(0.0, regained - ret["t"])
        dup = k
    # delivery (ISSUE 19): the poll-gap windows — for each token, the
    # wall time between its EMIT and the first successful poll whose
    # cursor covers it.  A streamed token nobody has pulled yet is the
    # CLIENT's latency, not the engine's: charging it to decode would
    # blame the replica for a slow poller.  Overlapping windows are
    # merged (one slow poll covering 10 emits is one gap, not ten).
    delivery = 0.0
    polls = sorted((p for p in r["poll_ts"] if p[0] is not None),
                   key=lambda p: p[0])
    if polls:
        intervals = []
        for i, emit in enumerate(ts):
            # first poll whose cursor is PAST token i delivered it; a
            # never-covered token (the client vanished mid-stream)
            # stays undelivered to the end of the record
            cover = next((p[0] for p in polls
                          if p[1] > i and p[0] >= emit), t1)
            lo = max(t0, emit)
            hi = min(t1, max(cover, emit))
            if hi > lo:
                intervals.append((lo, hi))
        intervals.sort()
        cur_lo = cur_hi = None
        for lo, hi in intervals:
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    delivery += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            delivery += cur_hi - cur_lo
    elif ts and final is not None and \
            (final.get("args") or {}).get("verdict") == "completed":
        # never-polled COMPLETED request: the budget between its last
        # token and its final verdict is the unary reply riding back —
        # delivery, not decode
        delivery = max(0.0, t1 - ts[-1])
    used = queue + prefill + swap + failover + delivery
    decode = max(0.0, total - used)
    r["phases"] = {"total_s": total, "queue_s": queue,
                   "prefill_s": prefill, "decode_s": decode,
                   "swap_s": swap, "failover_s": failover,
                   "delivery_s": delivery}
    r["dominant"] = max(
        ("queue_s", "prefill_s", "decode_s", "swap_s", "failover_s",
         "delivery_s"),
        key=lambda k: r["phases"][k])[:-2]


def lifecycle_check(reqs):
    """The trace laws (test-pinned, asserted by ``BENCH_MODE=serve``):
    every trace closes with EXACTLY ONE final verdict event, and that
    verdict is the trace's last event.  Returns the violation list
    (empty == lawful) and the set of open traces."""
    violations, open_traces = [], []
    for tr, r in sorted(reqs.items()):
        finals = [v for v in r["verdicts"]
                  if (v.get("args") or {}).get("final")]
        if not finals:
            open_traces.append(tr)
            continue
        if len(finals) > 1:
            violations.append(
                "trace %s has %d final verdicts (law: exactly one)"
                % (tr, len(finals)))
        if finals[-1]["_pos"] < r["last_pos"]:
            violations.append(
                "trace %s has events after its final verdict" % tr)
    return violations, open_traces


# -- fleet views -----------------------------------------------------------

def replica_matrix(reqs):
    """{replica: {admits, tokens, retries_out, verdict counts}} — the
    per-replica request matrix."""
    m = {}

    def row(tag):
        return m.setdefault(tag, {"admits": 0, "tokens": 0,
                                  "retries_out": 0, "verdicts": {}})

    for r in reqs.values():
        for seg in r["segments"]:
            rr = row(seg["replica"])
            rr["admits"] += 1
            rr["tokens"] += seg["tokens"]
        for ret in r["retries"]:
            row(ret["from"])["retries_out"] += 1
        final = r["final"]
        if final is not None:
            tag = ((final.get("args") or {}).get("replica")
                   or (r["segments"][-1]["replica"] if r["segments"]
                       else "-"))
            v = (final.get("args") or {}).get("verdict")
            vr = row(tag)["verdicts"]
            vr[v] = vr.get(v, 0) + 1
    return m


def verdict_latency_split(reqs):
    """{verdict: {n, ttft p50/p99, tpot p50/p99, queue p50/p99}} from
    the final verdict events' latency stamps."""
    groups = {}
    for r in reqs.values():
        if r["final"] is None:
            continue
        args = r["final"].get("args") or {}
        g = groups.setdefault(args.get("verdict"),
                              {"n": 0, "ttft": [], "tpot": [],
                               "queue": []})
        g["n"] += 1
        for key, field in (("ttft", "ttft_s"), ("tpot", "tpot_s"),
                           ("queue", "queue_wait_s")):
            if args.get(field) is not None:
                g[key].append(args[field])
    out = {}
    for v, g in groups.items():
        row = {"n": g["n"]}
        for key in ("ttft", "tpot", "queue"):
            vals = sorted(g[key])
            row[key + "_p50"] = _pct(vals, 0.5)
            row[key + "_p99"] = _pct(vals, 0.99)
        out[v] = row
    return out


def stream_latency_split(reqs):
    """TTFT percentiles split streamed-vs-unary (ISSUE 19).  A request
    is *streamed* iff at least one ``poll`` event named its trace.  The
    two classes measure DIFFERENT clocks on purpose: the streamed TTFT
    is submit → the first poll that DELIVERED a token (cursor past 0 —
    what a streaming client actually waits), while the unary TTFT is
    the engine's emit-side ``ttft_s`` stamp plus nothing (the whole
    reply rides back with the verdict, so first-token latency IS
    completion latency for that class).  The acceptance bar — streamed
    p50 well under the unary COMPLETION p50 — is what streaming buys."""
    streamed, unary, unary_total = [], [], []
    for r in reqs.values():
        polls = sorted((p for p in r["poll_ts"] if p[0] is not None),
                       key=lambda p: p[0])
        if polls:
            if r["submit_t"] is None:
                continue
            first = next((p[0] for p in polls if p[1] > 0), None)
            if first is not None:
                streamed.append(max(0.0, first - r["submit_t"]))
            continue
        if r["final"] is None:
            continue
        args = r["final"].get("args") or {}
        if args.get("ttft_s") is not None:
            unary.append(args["ttft_s"])
        if r["submit_t"] is not None:
            unary_total.append(max(0.0, r["final"]["t"] - r["submit_t"]))
    streamed.sort(), unary.sort(), unary_total.sort()
    return {
        "streamed": {"n": len(streamed),
                     "ttft_p50": _pct(streamed, 0.5),
                     "ttft_p99": _pct(streamed, 0.99)},
        "unary": {"n": len(unary),
                  "ttft_p50": _pct(unary, 0.5),
                  "ttft_p99": _pct(unary, 0.99),
                  "completion_p50": _pct(unary_total, 0.5),
                  "completion_p99": _pct(unary_total, 0.99)},
    }


def prefix_latency_split(reqs):
    """TTFT / queue-wait percentiles split by prefix-cache class
    (ISSUE 15): a ``hit`` request mapped shared pages and prefilled
    only its suffix, a ``miss`` paid the full prefill.  The cache's
    effect is thereby blameable per request like everything else in
    the §12 plane.  Read the TTFT split with the hardware in mind: on
    accelerators a hit skips the cached prefix's quadratic attention
    and should beat the miss class; on the CPU interpret path the
    static-pad suffix window plus the prefix gather make hit wall time
    >= miss — there the cache's measurable wins are the queue-wait
    split (admission capacity) and ``serving.prefill_tokens``.
    Requests that were never admitted (shed, expired-in-queue) have no
    class and are excluded."""
    groups = {}
    for r in reqs.values():
        if r["prefix_hit"] is None or r["final"] is None:
            continue
        args = r["final"].get("args") or {}
        g = groups.setdefault("hit" if r["prefix_hit"] else "miss",
                              {"n": 0, "ttft": [], "queue": [],
                               "prefix_len": [], "sampled": 0})
        g["n"] += 1
        if args.get("ttft_s") is not None:
            g["ttft"].append(args["ttft_s"])
        if args.get("queue_wait_s") is not None:
            g["queue"].append(args["queue_wait_s"])
        if r["prefix_len"]:
            g["prefix_len"].append(r["prefix_len"])
        if r["sampling"]:
            g["sampled"] += 1
    out = {}
    for cls, g in groups.items():
        ttft, queue = sorted(g["ttft"]), sorted(g["queue"])
        out[cls] = {
            "n": g["n"], "sampled": g["sampled"],
            "ttft_p50": _pct(ttft, 0.5), "ttft_p99": _pct(ttft, 0.99),
            "queue_p50": _pct(queue, 0.5),
            "queue_p99": _pct(queue, 0.99),
            "mean_prefix_len": (sum(g["prefix_len"])
                                / len(g["prefix_len"])
                                if g["prefix_len"] else 0),
        }
    return out


def failover_arcs(reqs):
    """Failed-over requests as linked arcs: one per retried trace —
    victim replica, survivor replica, tokens lost/regained, whether
    the arc completed, and the CONFIRMATION REASON the liveness
    machine typed on each hop (ISSUE 17: incarnation / kill_ack /
    fence_expiry; None for in-process ReplicaLost)."""
    arcs = []
    for tr, r in sorted(reqs.items()):
        if not r["retries"]:
            continue
        hops = [s["replica"] for s in r["segments"]]
        arcs.append({
            "trace": tr, "rid": r["rid"],
            "victims": [ret["from"] for ret in r["retries"]],
            "reasons": [ret.get("reason") for ret in r["retries"]],
            "path": hops,
            "survivor": hops[-1] if hops else None,
            "verdict": ((r["final"] or {}).get("args") or {})
            .get("verdict"),
            "failover_s": (r["phases"] or {}).get("failover_s"),
        })
    return arcs


def liveness_lanes(events):
    """Per-replica liveness lane (ISSUE 17), rebuilt from the
    trace-less liveness events the RPC proxies and the Router emit:
    suspicion spans (``suspect`` → ``suspect_clear`` or ``confirm``),
    the worst observed heartbeat gap, the confirmed death (typed
    reason), and fenced late-completion rejections.  These events
    carry no trace id by design — they are replica news, not request
    lifecycle hops — so they never appear in ``build_requests``;
    this lane is their home."""
    lanes = {}

    def lane(tag):
        return lanes.setdefault(tag, {
            "replica": tag, "suspicions": 0, "spans": [],
            "open_suspect_t": None, "max_gap_s": 0.0,
            "confirmed": None, "fenced": 0, "fenced_tokens": 0})

    for e in events:
        ev = e.get("event")
        if ev not in ("suspect", "suspect_clear", "confirm", "fenced"):
            continue
        args = e.get("args") or {}
        tag = args.get("replica")
        if tag is None:
            continue
        ln = lane(tag)
        t = e.get("t")
        if ev == "suspect":
            ln["suspicions"] += 1
            ln["open_suspect_t"] = t
            ln["max_gap_s"] = max(ln["max_gap_s"],
                                  args.get("gap_s") or 0.0)
        elif ev == "suspect_clear":
            if ln["open_suspect_t"] is not None and t is not None:
                ln["spans"].append(
                    {"t": ln["open_suspect_t"],
                     "dur_s": max(0.0, t - ln["open_suspect_t"]),
                     "cleared": True})
            ln["open_suspect_t"] = None
            ln["max_gap_s"] = max(ln["max_gap_s"],
                                  args.get("gap_s") or 0.0)
        elif ev == "confirm":
            if ln["open_suspect_t"] is not None and t is not None:
                ln["spans"].append(
                    {"t": ln["open_suspect_t"],
                     "dur_s": max(0.0, t - ln["open_suspect_t"]),
                     "cleared": False})
                ln["open_suspect_t"] = None
            ln["confirmed"] = {"t": t, "reason": args.get("reason")}
        elif ev == "fenced":
            ln["fenced"] += 1
            ln["fenced_tokens"] += args.get("tokens") or 0
    return lanes


def alert_lanes(events):
    """Fired alert-rule events (ISSUE 18), in fleet time order.  Like
    liveness events these are trace-less replica news — invisible to
    ``build_requests`` — so the alerts lane is their only rendering;
    each row names the rule, severity, the metric that tripped it, the
    observed value, and the pid that fired it."""
    out = []
    for e in events:
        if e.get("event") != "alert":
            continue
        args = e.get("args") or {}
        out.append({"t": e.get("t"), "pid": e.get("_pid"),
                    "rule": args.get("rule"),
                    "severity": args.get("severity"),
                    "metric": args.get("metric"),
                    "value": args.get("value")})
    out.sort(key=lambda a: a["t"] or 0)
    return out


def blame(reqs, slo_ttft=None):
    """The SLO breach blame list: every request whose terminal verdict
    is not ``completed``, every failed-over request, and (with
    ``slo_ttft``) every completed request whose TTFT breached it —
    each decomposed into its phase budget with the dominant phase and
    the responsible replica named."""
    out = []
    for tr, r in sorted(reqs.items()):
        final = r["final"]
        if final is None:
            continue
        args = final.get("args") or {}
        verdict = args.get("verdict")
        breach = None
        if verdict != "completed":
            breach = verdict
        elif r["retries"]:
            breach = "failed_over"
        elif slo_ttft is not None and \
                (args.get("ttft_s") or 0.0) > slo_ttft:
            breach = "ttft_over_slo"
        if breach is None:
            continue
        phases = r["phases"] or {}
        dominant = r.get("dominant")
        if r["retries"]:
            blamed = r["retries"][-1]["from"]
            why = "replica %s lost mid-decode" % blamed
        elif verdict in REFUSAL_VERDICTS:
            blamed = ((r["verdicts"][0].get("args") or {})
                      .get("replica") if r["verdicts"] else None)
            why = "intake refused (%s)" % verdict
        elif dominant == "queue":
            blamed = (r["segments"][0]["replica"] if r["segments"]
                      else args.get("replica"))
            why = "queue wait dominated"
        else:
            blamed = (r["segments"][-1]["replica"] if r["segments"]
                      else args.get("replica"))
            why = "%s phase dominated" % (dominant or "?")
        out.append({"trace": tr, "rid": r["rid"], "breach": breach,
                    "verdict": verdict, "phases": phases,
                    "dominant": dominant, "replica": blamed,
                    "why": why})
    out.sort(key=lambda b: -(b["phases"].get("total_s") or 0.0)
             if b["phases"] else 0.0)
    return out


def accounting(data, reqs):
    """Goodput vs raw tokens, traced-vs-counter reconciliation, and
    flops/bytes-per-token from the compile-time cost attribution joined
    with the measured execution counts."""
    tokens = goodput = requests = dropped = scale_repairs = 0
    spec = {"draft_tokens": 0, "accepted": 0, "rejected": 0,
            "rollbacks": 0}
    for c in data["counters"].values():
        tokens += c.get("serving.tokens", 0)
        goodput += c.get("serving.goodput", 0)
        requests += c.get("serving.requests", 0)
        dropped += c.get("serving.trace_dropped", 0)
        scale_repairs += c.get("serving.kv.scale_repairs", 0)
        for key in spec:
            spec[key] += c.get("serving.spec." + key, 0)
    traced = sum(len(r["token_ts"]) for r in reqs.values())
    # fleet tokens-per-dispatch (ISSUE 16): decode tokens over decode
    # dispatches — 1.0 without speculation, > 1 when accepted drafts
    # multiply what each donated dispatch commits
    decode_steps = sum(s.get("decode_steps") or 0
                       for s in data["status"].values())
    prefills = sum(s.get("prefills") or 0
                   for s in data["status"].values())
    flops = bytes_ = 0.0
    have_cost = False
    for snap in data["status"].values():
        cost = snap.get("cost") or {}
        dec, pre = cost.get("decode") or {}, cost.get("prefill") or {}
        if dec.get("flops") is not None:
            have_cost = True
            flops += (dec.get("flops", 0.0)
                      * (snap.get("decode_steps") or 0)
                      + pre.get("flops", 0.0)
                      * (snap.get("prefills") or 0))
            bytes_ += (dec.get("bytes_accessed", 0.0)
                       * (snap.get("decode_steps") or 0)
                       + pre.get("bytes_accessed", 0.0)
                       * (snap.get("prefills") or 0))
    return {
        "tokens": tokens, "goodput": goodput, "requests": requests,
        "traced_tokens": traced,
        "tokens_match": traced == tokens and not dropped
        and not data["req_dropped"],
        "trace_dropped": dropped + data["req_dropped"],
        "goodput_fraction": (goodput / tokens) if tokens else None,
        "flops_per_token": (flops / tokens) if have_cost and tokens
        else None,
        "bytes_per_token": (bytes_ / tokens) if have_cost and tokens
        else None,
        "kv_scale_repairs": scale_repairs,
        "spec": spec if spec["draft_tokens"] else None,
        "acceptance_rate": (spec["accepted"] / spec["draft_tokens"]
                            if spec["draft_tokens"] else None),
        "tokens_per_dispatch": ((tokens - prefills) / decode_steps
                                if decode_steps else None),
    }


# -- merged chrome trace ---------------------------------------------------

def merged_trace(data, reqs):
    """One chrome-tracing document for the fleet: pid = replica (tid =
    decode slot; residency segments as spans, tokens as thread-scoped
    instants, failover arcs as flow arrows crossing replica tracks,
    hot-swap pauses on a dedicated row), plus each process's recent
    decode-step spans (the flight ring) on per-process tracks.  Returns
    ``(doc, t0_unix)``."""
    tags = sorted({s["replica"] for r in reqs.values()
                   for s in r["segments"] if s["replica"] is not None})
    pid_of = {tag: i + 1 for i, tag in enumerate(tags)}
    stamps = [r["submit_t"] for r in reqs.values()
              if r["submit_t"] is not None]
    stamps += [rec["t_unix"] for _, recs in data["flights"]
               for rec in recs if rec.get("t_unix")]
    t0 = min(stamps) if stamps else 0.0

    def us(t):
        return (t - t0) * 1e6

    events = []
    for tag, pid in pid_of.items():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": "replica %s" % tag}})
    flow_id = 0
    for tr, r in sorted(reqs.items()):
        label = "req %s" % (r["rid"] if r["rid"] is not None else tr)
        final_args = (r["final"] or {}).get("args") or {}
        prev_end = None
        for i, seg in enumerate(r["segments"]):
            pid = pid_of.get(seg["replica"], 0)
            tid = seg["slot"] if seg["slot"] is not None else 0
            end = seg["end"]
            if end is None:
                seg_ts = [t for t in r["token_ts"] if t >= seg["t"]]
                end = seg_ts[-1] if seg_ts else seg["t"]
            events.append({
                "name": label, "cat": "request", "ph": "X",
                "pid": pid, "tid": tid, "ts": us(seg["t"]),
                "dur": max(1.0, (end - seg["t"]) * 1e6),
                "args": {"trace": tr, "segment": i,
                         "tokens": seg["tokens"],
                         "verdict": final_args.get("verdict")}})
            if prev_end is not None:
                # the failover arc: an arrow from the victim segment's
                # end to the survivor's admit
                flow_id += 1
                events.append({"name": "failover", "cat": "request",
                               "ph": "s", "id": flow_id, "pid":
                               prev_end[0], "tid": prev_end[1],
                               "ts": us(prev_end[2])})
                events.append({"name": "failover", "cat": "request",
                               "ph": "f", "bp": "e", "id": flow_id,
                               "pid": pid, "tid": tid,
                               "ts": us(seg["t"])})
            prev_end = (pid, tid, end)
        for t in r["token_ts"]:
            seg = next((s for s in reversed(r["segments"])
                        if s["t"] <= t), None)
            if seg is None:
                continue
            events.append({"name": "token", "cat": "token", "ph": "i",
                           "s": "t",
                           "pid": pid_of.get(seg["replica"], 0),
                           "tid": seg["slot"] or 0, "ts": us(t),
                           "args": {"trace": tr}})
    for e in (e for e in data["events"] if e.get("event") == "swap"):
        args = e.get("args") or {}
        pid = pid_of.get(args.get("replica"), 0)
        events.append({"name": "swap epoch %s%s"
                       % (args.get("epoch"),
                          "" if args.get("ok") else " (ROLLBACK)"),
                       "cat": "swap", "ph": "X", "pid": pid,
                       "tid": SWAP_TID, "ts": us(e.get("t", t0)),
                       "dur": max(1.0, (args.get("dur_s") or 0.0)
                                  * 1e6),
                       "args": {"traces": args.get("traces")}})
    for i, (proc, recs) in enumerate(data["flights"]):
        pid = PROC_TRACK_BASE + i
        slot, attempt, ppid = proc
        label = "pid %s" % ppid if slot is None else \
            "slot %s attempt %s pid %s" % (slot, attempt, ppid)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "args": {"name": "process %s (decode steps)"
                                % label}})
        for rec in recs:
            where = rec.get("where") or "step"
            ts = us(rec.get("t_unix", t0))
            dur = (rec.get("dispatch_s") or 0.0) * 1e6
            events.append({"name": where + ".dispatch", "cat": "step",
                           "ph": "X", "pid": pid, "tid": 0, "ts": ts,
                           "dur": dur,
                           "args": {"step": rec.get("step")}})
            if rec.get("sync_s") is not None:
                events.append({"name": where + ".sync", "cat": "step",
                               "ph": "X", "pid": pid, "tid": 0,
                               "ts": ts + dur,
                               "dur": rec["sync_s"] * 1e6,
                               "args": {"step": rec.get("step")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}, t0


# -- the report ------------------------------------------------------------

def analyze(run_dir, slo_ttft=None):
    """Load + reconstruct + judge: the structured fleet report
    (``render`` prints it; ``BENCH_MODE=serve`` asserts on it)."""
    data = load_serve(run_dir)
    reqs = build_requests(data["events"])
    violations, open_traces = lifecycle_check(reqs)
    arcs = failover_arcs(reqs)
    journal_retries = [d for d in data["journal"]
                       if d.get("event") == "retry"]
    # an arc is LINKED when the same trace names both a victim and a
    # different survivor — a victim killed while still queued (no
    # residency segment on the dead replica) links exactly the same way
    linked_arcs = sum(
        1 for a in arcs
        if a["victims"] and a["survivor"] is not None
        and a["survivor"] not in a["victims"])
    return {
        "data": data, "requests": reqs,
        "lifecycle": {"violations": violations,
                      "open_traces": open_traces,
                      "ok": not violations and not open_traces},
        "matrix": replica_matrix(reqs),
        "latency": verdict_latency_split(reqs),
        "stream": stream_latency_split(reqs),
        "prefix": prefix_latency_split(reqs),
        "arcs": arcs, "linked_arcs": linked_arcs,
        "journal_retries": journal_retries,
        "liveness": liveness_lanes(data["events"]),
        "alerts": alert_lanes(data["events"]),
        "blame": blame(reqs, slo_ttft),
        "accounting": accounting(data, reqs),
    }


def render(rep, out=sys.stdout):
    data = rep["data"]
    reqs = rep["requests"]
    out.write("== SERVE REPORT %s ==\n" % data["run_dir"])
    out.write("  %d trace(s), %d journal line(s), %d replica stream "
              "process(es)\n"
              % (len(reqs), len(data["journal"]),
                 len(data["counters"])))
    for note in data["notes"]:
        out.write("  %s\n" % note)
    if data["req_dropped"]:
        out.write("  WARNING: %d request event(s) evicted before any "
                  "stream line carried them — lifecycles may have "
                  "gaps\n" % data["req_dropped"])
    lc = rep["lifecycle"]
    if lc["ok"]:
        out.write("  lifecycle laws: every trace closed with exactly "
                  "one final verdict\n")
    else:
        for v in lc["violations"]:
            out.write("  LIFECYCLE VIOLATION: %s\n" % v)
        for tr in lc["open_traces"]:
            out.write("  OPEN TRACE (no final verdict): %s\n" % tr)

    out.write("\n-- per-replica request matrix --\n")
    # per-replica dispatch accounting from the status snapshots: the
    # tokens-per-dispatch column (ISSUE 16) reads 1.00 on a
    # non-speculative replica and > 1 where accepted drafts multiplied
    # what each donated decode dispatch committed
    snaps = {}
    for snap in data["status"].values():
        if snap.get("replica"):
            snaps[snap["replica"]] = snap
    rows = []
    for tag in sorted(rep["matrix"]):
        m = rep["matrix"][tag]
        snap = snaps.get(tag) or {}
        steps = snap.get("decode_steps") or 0
        pre = snap.get("prefills") or 0
        tpd = ("%.2f" % ((m["tokens"] - pre) / steps)) if steps else "-"
        kv_bpt = snap.get("kv_bytes_per_token")
        rows.append((tag, m["admits"], m["tokens"], tpd,
                     snap.get("kv_dtype") or "-",
                     "%.0f" % kv_bpt if kv_bpt is not None else "-",
                     m["retries_out"],
                     "  ".join("%s=%d" % kv
                               for kv in sorted(m["verdicts"].items()))
                     or "-"))
    _tr._table(("replica", "admits", "tokens", "tok/disp", "kv",
                "kvB/tok", "lost", "verdicts"), rows, out)

    out.write("\n-- latency by verdict class --\n")
    rows = []
    for v in sorted(rep["latency"]):
        g = rep["latency"][v]
        rows.append((v, g["n"], _tr._fmt_s(g["ttft_p50"]),
                     _tr._fmt_s(g["ttft_p99"]),
                     _tr._fmt_s(g["tpot_p50"]),
                     _tr._fmt_s(g["queue_p50"]),
                     _tr._fmt_s(g["queue_p99"])))
    _tr._table(("verdict", "n", "ttft_p50", "ttft_p99", "tpot_p50",
                "queue_p50", "queue_p99"), rows, out)

    st = rep.get("stream") or {}
    if (st.get("streamed") or {}).get("n"):
        out.write("\n-- TTFT: streamed vs unary (ISSUE 19) --\n")
        s, u = st["streamed"], st["unary"]
        rows = [("streamed", s["n"], _tr._fmt_s(s["ttft_p50"]),
                 _tr._fmt_s(s["ttft_p99"]), "-", "-"),
                ("unary", u["n"], _tr._fmt_s(u["ttft_p50"]),
                 _tr._fmt_s(u["ttft_p99"]),
                 _tr._fmt_s(u["completion_p50"]),
                 _tr._fmt_s(u["completion_p99"]))]
        _tr._table(("class", "n", "ttft_p50", "ttft_p99",
                    "compl_p50", "compl_p99"), rows, out)
        out.write("  (streamed TTFT = submit -> first poll that "
                  "delivered a token; a unary reply only lands with "
                  "its verdict, so its first-token latency is its "
                  "completion latency)\n")

    if rep["prefix"]:
        out.write("\n-- latency by prefix class (ISSUE 15) --\n")
        rows = []
        for cls in sorted(rep["prefix"]):
            g = rep["prefix"][cls]
            rows.append((cls, g["n"], g["sampled"],
                         "%.1f" % g["mean_prefix_len"],
                         _tr._fmt_s(g["ttft_p50"]),
                         _tr._fmt_s(g["ttft_p99"]),
                         _tr._fmt_s(g["queue_p50"]),
                         _tr._fmt_s(g["queue_p99"])))
        _tr._table(("prefix", "n", "sampled", "avg_len", "ttft_p50",
                    "ttft_p99", "queue_p50", "queue_p99"), rows, out)
        c = {}
        for cc in data["counters"].values():
            for key in ("serving.prefix.hits", "serving.prefix.miss",
                        "serving.prefix.shared_pages",
                        "serving.prefix.cow_copies",
                        "serving.prefix.evictions",
                        "serving.prefill_tokens",
                        "serving.sampling.requests"):
                if cc.get(key):
                    c[key] = c.get(key, 0) + cc[key]
        if c:
            out.write("  " + "  ".join(
                "%s=%d" % kv for kv in sorted(c.items())) + "\n")

    if rep["liveness"]:
        out.write("\n-- per-replica liveness lane (ISSUE 17) --\n")
        rows = []
        for tag in sorted(rep["liveness"]):
            ln = rep["liveness"][tag]
            conf = ln["confirmed"]
            spans = len(ln["spans"]) + (
                1 if ln["open_suspect_t"] is not None else 0)
            rows.append((tag, ln["suspicions"], spans,
                         _tr._fmt_s(ln["max_gap_s"]),
                         conf["reason"] if conf else "-",
                         ln["fenced"], ln["fenced_tokens"]))
        _tr._table(("replica", "suspicions", "spans", "max_hb_gap",
                    "confirmed", "fenced", "fenced_tok"), rows, out)

    if rep.get("alerts"):
        out.write("\n-- fired alerts (ISSUE 18) --\n")
        t0 = min((a["t"] for a in rep["alerts"]
                  if a["t"] is not None), default=None)
        rows = []
        for a in rep["alerts"]:
            rows.append((
                _tr._fmt_s(a["t"] - t0) if a["t"] is not None
                and t0 is not None else "-",
                a["severity"] or "-", a["rule"] or "?",
                a["metric"] or "-",
                a["value"] if a["value"] is not None else "-",
                a["pid"] if a["pid"] is not None else "-"))
        _tr._table(("t+", "severity", "rule", "metric", "value",
                    "pid"), rows, out)

    if rep["arcs"]:
        out.write("\n-- failover arcs (linked by trace id) --\n")
        for a in rep["arcs"]:
            reason = ", ".join(x for x in a.get("reasons") or [] if x)
            out.write("  req %s [%s]: %s -> %s (%s, failover cost %s"
                      "%s)\n"
                      % (a["rid"] if a["rid"] is not None
                         else a["trace"],
                         a["trace"], " + ".join(a["victims"]),
                         a["survivor"], a["verdict"],
                         _tr._fmt_s(a["failover_s"]),
                         ", confirmed %s" % reason if reason else ""))

    if rep["blame"]:
        out.write("\n-- SLO breach blame --\n")
        for b in rep["blame"]:
            p = b["phases"] or {}
            out.write("  req %s (%s): %s — dominant %s; %s\n"
                      % (b["rid"] if b["rid"] is not None
                         else b["trace"], b["breach"],
                         "  ".join("%s %s" % (k[:-2],
                                              _tr._fmt_s(p.get(k)))
                                   for k in ("queue_s", "prefill_s",
                                             "decode_s", "swap_s",
                                             "failover_s",
                                             "delivery_s")
                                   if p.get(k)),
                         b["dominant"], b["why"]))
        blamed = {}
        for b in rep["blame"]:
            if b["replica"] is not None:
                blamed[b["replica"]] = blamed.get(b["replica"], 0) + 1
        if blamed:
            out.write("  blame by replica: " + "  ".join(
                "%s=%d" % kv for kv in sorted(blamed.items())) + "\n")
    else:
        out.write("\n  no SLO breaches: every request completed "
                  "without failover\n")

    acc = rep["accounting"]
    out.write("\n-- goodput / cost --\n")
    out.write("  tokens=%d goodput=%d (%.1f%%)  traced=%d (%s)\n"
              % (acc["tokens"], acc["goodput"],
                 100.0 * (acc["goodput_fraction"] or 0.0),
                 acc["traced_tokens"],
                 "bit-exact" if acc["tokens_match"]
                 else "MISMATCH vs serving.tokens"))
    if acc.get("kv_scale_repairs"):
        out.write("  kv quantization: %d scale-poison repair(s) — "
                  "victims re-prefilled on the finite guard (ISSUE "
                  "20)\n" % acc["kv_scale_repairs"])
    if acc["flops_per_token"] is not None:
        out.write("  cost per token: %.3g flops, %.3g bytes accessed "
                  "(compile-time attribution x measured executions)\n"
                  % (acc["flops_per_token"], acc["bytes_per_token"]))
    if acc.get("spec"):
        sp = acc["spec"]
        out.write("  spec decode: drafted=%d accepted=%d rejected=%d "
                  "rollbacks=%d  acceptance=%.1f%%  "
                  "tokens/dispatch=%s\n"
                  % (sp["draft_tokens"], sp["accepted"],
                     sp["rejected"], sp["rollbacks"],
                     100.0 * (acc["acceptance_rate"] or 0.0),
                     "%.2f" % acc["tokens_per_dispatch"]
                     if acc["tokens_per_dispatch"] is not None
                     else "-"))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Merge a serving fleet's artifacts (router journal "
        "+ replica streams + postmortems) into one report: request "
        "lifecycles, failover arcs, SLO breach blame, goodput/cost, "
        "merged chrome trace")
    ap.add_argument("run_dir", help="run dir holding the telemetry "
                    "tree (stream-slot*.jsonl, router-journal*.jsonl, "
                    "postmortems)")
    ap.add_argument("--slo-ttft", type=float, default=None,
                    help="also blame COMPLETED requests whose TTFT "
                    "exceeded this many seconds")
    ap.add_argument("--trace-out", default=None,
                    help="write the merged fleet chrome trace "
                    "(Perfetto-loadable) to this path")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        sys.stderr.write("serve_report.py: %s is not a run dir\n"
                         % args.run_dir)
        return 2
    rep = analyze(args.run_dir, slo_ttft=args.slo_ttft)
    if not rep["requests"]:
        sys.stderr.write("serve_report.py: no request traces under %s "
                         "(serve with telemetry enabled? "
                         "MXTPU_TELEMETRY / --telemetry-dir)\n"
                         % args.run_dir)
        return 1
    render(rep)
    if args.trace_out:
        doc, t0 = merged_trace(rep["data"], rep["requests"])
        with open(args.trace_out, "w") as f:
            json.dump(doc, f)
        spans = sum(1 for e in doc["traceEvents"] if e["ph"] == "X")
        sys.stdout.write("\n  merged trace: %s (%d span(s) across %d "
                         "replica track(s), t0=%.3f)\n"
                         % (args.trace_out, spans, sum(
                             1 for e in doc["traceEvents"]
                             if e["ph"] == "M"
                             and str(e["args"].get("name", ""))
                             .startswith("replica")), t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
