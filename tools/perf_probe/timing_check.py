"""Tunnel completion-barrier probe (PERF.md §1): shows block_until_ready
returning early vs a forced host fetch on a known-FLOPs matmul chain."""
import time
import jax, jax.numpy as jnp
import numpy as np

N = 8192
@jax.jit
def f(a, b):
    for _ in range(10):
        a = jnp.tanh(a @ b)
    return a

a = jax.random.normal(jax.random.PRNGKey(0), (N, N), jnp.bfloat16)
b = jax.random.normal(jax.random.PRNGKey(1), (N, N), jnp.bfloat16)
o = f(a, b); _ = np.asarray(o[0, 0])
for ITER in (5, 20):
    t0 = time.perf_counter()
    o = f(o, b)
    for _ in range(ITER - 1):
        o = f(o, b)
    _ = np.asarray(o[0, 0])   # scalar fetch forces the whole chain
    dt = time.perf_counter() - t0
    fl = 2.0 * N**3 * 10 * ITER
    print("ITER=%d: %.3fs -> %.1f TFLOP/s" % (ITER, dt, fl / dt / 1e12))
