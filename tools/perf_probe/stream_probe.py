"""BENCH_MODE=stream body: streaming ingest vs in-memory DataLoader.

Builds ONE synthetic shard set (float32 feature vectors + labels packed
as RecordIO records across several shards), then runs the same fused
MLP fit loop (steptrace.build_module's network) twice:

- **in-memory**: batches materialized up front (the PR-1 baseline —
  decode cost excluded by construction);
- **streaming**: batches decoded from the on-disk shards through
  ``mxnet_tpu.stream.StreamLoader``'s worker pool, re-iterated per
  epoch through the SAME device prefetcher.

Contracts (bench.py BENCH_MODE=stream hard-fails on violation):

- steady-state fused-step wall time from disk within
  ``MXTPU_STREAM_BENCH_MAX_RATIO`` (default 1.10) of in-memory — the
  decode pool must hide the decode behind compute;
- ``io.queue_wait`` p99 bounded (< one in-memory step) — the consumer
  is never starved in steady state;
- exactly 1.0 dispatch/step and 0 steady-state recompiles — streaming
  feeds the same donated program, changing nothing above the batch.

The ratio is the median over alternating paired segments (the
BENCH_MODE=telemetry methodology): on a shared CPU box an absolute
single-shot comparison of ~0.3 ms steps is all scheduler noise.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def build_shard_set(root, n_batches=8, batch=64, dim=32, classes=4,
                    n_shards=4):
    """The synthetic stream: same data distribution as
    steptrace.build_module, packed as fixed-size records (x float32[dim]
    | y float32) across ``n_shards`` RecordIO shards."""
    import numpy as np
    from mxnet_tpu import stream

    rs = np.random.RandomState(0)
    n = n_batches * batch
    X = rs.randn(n, dim).astype(np.float32)
    y = rs.randint(0, classes, size=n).astype(np.float32)
    w = stream.ShardSetWriter(root)
    per = (n + n_shards - 1) // n_shards
    for k in range(n_shards):
        lo, hi = k * per, min((k + 1) * per, n)
        w.write_recordio_shard(
            X[i].tobytes() + y[i].tobytes() for i in range(lo, hi))
    w.seal()
    return stream.load_shard_set(root), X, y


def _decode(dim):
    import numpy as np

    def decode(raw):
        x = np.frombuffer(raw[:dim * 4], dtype=np.float32)
        y = np.frombuffer(raw[dim * 4:], dtype=np.float32)[0]
        return x, y
    return decode


def _decode_batch(dim):
    """Vectorized per-task decode (StreamLoader's ``decode_batch_fn``):
    one frombuffer+reshape over the whole chunk instead of a Python
    call per record — fixed-size records should always decode this
    way (DATA.md "Decode functions")."""
    import numpy as np

    def decode_batch(raws):
        arr = np.frombuffer(b"".join(raws), dtype=np.float32)
        arr = arr.reshape(len(raws), dim + 1)
        return list(zip(arr[:, :dim], arr[:, dim]))
    return decode_batch


def run(n_batches=None, pairs=None):
    import numpy as np  # noqa: F401 (decode closure)
    import steptrace as _steptrace
    import mxnet_tpu as mx
    from mxnet_tpu import profiler, stream, telemetry

    import shutil
    import tempfile

    batch, dim, classes = 64, 32, 4
    n_batches = n_batches or max(
        8, int(os.environ.get("BENCH_STREAM_BATCHES", "64")))
    pairs = pairs or max(3, int(os.environ.get("BENCH_PAIRS", "9")))

    root = tempfile.mkdtemp(prefix="stream-probe-")
    try:
        shard_set, X, y = build_shard_set(root, n_batches, batch, dim,
                                          classes)
        mod, train = _steptrace.build_module(
            batch=batch, dim=dim, classes=classes, n_batches=n_batches)

        # THE comparison the contract states: the same fused fit loop
        # fed by (a) the PR-1 in-memory DataLoader — ArrayDataset +
        # batchify + device prefetcher — and (b) the StreamLoader
        # decoding the same records from disk shards through its worker
        # pool into the SAME device prefetcher.  Both sides pay
        # batchify + h2d per batch; streaming adds shard reads + decode,
        # which the pool must hide.
        from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
        mem_loader = DataLoader(ArrayDataset(X, y), batch_size=batch,
                                last_batch="keep")
        # chunk 256 = 4 batches per decode task: task management and
        # queue hops amortize 4x (per-record work is already one
        # vectorized numpy pass), which is what holds the 1.10x contract
        # at CPU-microbench step sizes; DATA.md "Sizing" carries the
        # guidance
        loader = stream.StreamLoader(
            shard_set, batch, decode_batch_fn=_decode_batch(dim),
            epoch=0, rank=0, world_size=1, seed=0,
            chunk_records=256, queue_depth=6)

        def to_databatch(b):
            return mx.io.DataBatch(data=[b[0]], label=[b[1]])

        def run_epoch(it):
            n = 0
            t0 = time.perf_counter()
            for b in it:
                mod.fit_step(to_databatch(b))
                n += 1
            return n, time.perf_counter() - t0

        def stream_epoch(epoch):
            loader.set_epoch(epoch)
            return run_epoch(loader)

        def mem_epoch():
            return run_epoch(mem_loader)

        # warm: trace+compile+allocator, one full pass per side so the
        # pool/readers/prefetcher are in steady state
        mem_epoch()
        stream_epoch(0)

        # the measured segments (one full epoch each side per pair):
        # alternate which side goes first so drift can't systematically
        # land on one side; the MEDIAN ratio kills the outliers a
        # shared box produces
        ratios, mem_s, stream_s = [], [], []
        for i in range(pairs):
            if i % 2:
                n, t = mem_epoch()
                m = t / n
                n, t = stream_epoch(i + 1)
                s = t / n
            else:
                n, t = stream_epoch(i + 1)
                s = t / n
                n, t = mem_epoch()
                m = t / n
            mem_s.append(m)
            stream_s.append(s)
            ratios.append(s / m)

        ratios.sort()
        mem_s.sort()
        stream_s.sort()
        ratio = ratios[len(ratios) // 2]

        # contract segment under reset counters: dispatch/recompile laws
        # + the io.queue_wait bound, measured over fresh telemetry
        telemetry.reset()
        profiler.reset_step_stats()
        n, _ = stream_epoch(100)
        stats = profiler.step_stats()
        rep = telemetry.report()
        ioq = (rep["phases"].get("io.queue_wait") or {})
        mem_step = mem_s[len(mem_s) // 2]
        return {
            "ratio_stream_vs_mem": round(ratio, 4),
            "ratio_pairs": [round(r, 4) for r in ratios],
            "mem_step_ms": round(mem_step * 1e3, 4),
            "stream_step_ms": round(stream_s[len(stream_s) // 2] * 1e3,
                                    4),
            "contract_steps": n,
            "dispatches_per_step": stats["dispatch_count"] / max(1, n),
            "compile_count": stats["compile_count"],
            "io_queue_wait_p99_ms": round(
                (ioq.get("p99") or 0.0) * 1e3, 4),
            "io_queue_wait_bound_ms": round(mem_step * 1e3, 4),
            "io_records": rep["counters"].get("io.records", 0),
            "io_bytes": rep["counters"].get("io.bytes", 0),
            "io_torn_records": rep["counters"].get("io.torn_records", 0),
            "max_ratio": float(os.environ.get(
                "MXTPU_STREAM_BENCH_MAX_RATIO", "1.10")),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def check(result):
    """The hard contracts — one home, shared by BENCH_MODE=stream and
    the tier-1 sibling test (which loosens max_ratio via env for noise
    headroom, never the structural laws)."""
    if result["dispatches_per_step"] != 1.0:
        raise AssertionError(
            "streaming fit loop dispatched %.3f programs/step "
            "(contract: exactly 1.0 — the stream feeds the same donated "
            "program)" % result["dispatches_per_step"])
    if result["compile_count"] != 0:
        raise AssertionError(
            "streaming fit loop recompiled %d time(s) in steady state"
            % result["compile_count"])
    if result["io_queue_wait_p99_ms"] >= result["io_queue_wait_bound_ms"]:
        raise AssertionError(
            "io.queue_wait p99 %.3f ms >= one in-memory step %.3f ms: "
            "the decode pool starves the consumer"
            % (result["io_queue_wait_p99_ms"],
               result["io_queue_wait_bound_ms"]))
    if result["io_torn_records"]:
        raise AssertionError(
            "synthetic shard set produced %d torn records"
            % result["io_torn_records"])
    if result["ratio_stream_vs_mem"] > result["max_ratio"]:
        raise AssertionError(
            "steady-state streaming step %.4fx the in-memory step "
            "(contract: <= %.2fx — decode must hide behind the worker "
            "pool)" % (result["ratio_stream_vs_mem"],
                       result["max_ratio"]))


if __name__ == "__main__":
    r = run()
    check(r)
    print(json.dumps(r))
