"""Fault-tolerance off the hot path: what does it actually cost?

Two measurements backing PERF.md §12 (CPU micro-bench, same MLP fit
loop family as steptrace.py but sized so checkpoint serialization and
XLA compilation are non-trivial):

- **per-checkpoint step stall** — wall time the training loop spends
  blocked inside ``Module.save_checkpoint`` at a step boundary, sync
  (serialize + sha256 + fsync + rename inline) vs async (host snapshot
  + bounded enqueue; the write overlaps the following steps).  p50/p99
  over many checkpoints, with a few train steps between saves so the
  async writer drains the way it does in production.
- **time-to-first-step** — fresh subprocess from backend-ready to the
  first completed ``fit_step``: cold (empty cache: trace + XLA compile)
  vs warm (same cache dir: the fused step deserializes from the AOT
  executable cache — on CPU the donation-free twin, with the donated
  program compiled in the background and hot-swapped in; donation-free
  eager-op programs hit jax's persistent compile cache) — the restart
  path tools/launch.py sets up via ``--aot-cache-dir``.

Usage: JAX_PLATFORMS=cpu python tools/perf_probe/restart_probe.py
Prints one JSON object: {"stall": {...}, "ttfs": {...}}.
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_module(batch=64, dim=256, hidden=512, classes=16, n_batches=4):
    """~0.4 M-param MLP: big enough that a checkpoint write and the
    fused-step compile are both worth measuring, small enough for CI —
    the steptrace fixture, one layer deeper and much wider."""
    import steptrace
    mod, train = steptrace.build_module(batch=batch, dim=dim,
                                        classes=classes, hidden=hidden,
                                        depth=3, n_batches=n_batches)
    return mod, list(train)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def measure_stalls(mode, n_ckpt=None):
    """Per-checkpoint stall for one mode ('sync'|'async'): the wall time
    ``save_checkpoint`` blocks the step loop, measured at a real step
    boundary with training steps between checkpoints."""
    from mxnet_tpu import checkpoint as ckpt

    n_ckpt = n_ckpt or int(os.environ.get("BENCH_RESTART_CKPTS", "15"))
    tmpdir = tempfile.mkdtemp(prefix="restart-probe-%s-" % mode)
    mod, batches = build_module()
    for _ in range(2):  # warm: trace + compile + allocator steady state
        for b in batches:
            mod.fit_step(b)
    prefix = os.path.join(tmpdir, "ck")
    prev = os.environ.get("MXTPU_ASYNC_CKPT")
    os.environ["MXTPU_ASYNC_CKPT"] = "1" if mode == "async" else "0"
    stalls = []
    try:
        for i in range(n_ckpt):
            for b in batches:  # the writer drains behind these steps
                mod.fit_step(b)
            t0 = time.perf_counter()
            mod.save_checkpoint(prefix, i + 1, save_optimizer_states=True,
                                keep_last=4)
            stalls.append(time.perf_counter() - t0)
        # drain OUTSIDE the timed region: flush cost is paid once at
        # epoch/run end, not per checkpoint — that is the design
        ckpt.flush_async()
    finally:
        if prev is None:
            os.environ.pop("MXTPU_ASYNC_CKPT", None)
        else:
            os.environ["MXTPU_ASYNC_CKPT"] = prev
        shutil.rmtree(tmpdir, ignore_errors=True)
    stalls.sort()
    return {
        "mode": mode, "checkpoints": n_ckpt,
        "p50_ms": round(_pct(stalls, 0.50) * 1e3, 3),
        "p99_ms": round(_pct(stalls, 0.99) * 1e3, 3),
        "mean_ms": round(sum(stalls) / len(stalls) * 1e3, 3),
        "max_ms": round(stalls[-1] * 1e3, 3),
    }


def _ttfs_child():
    """Internal --ttfs-child mode: one fresh process's restart cost.
    The clock starts AFTER backend init (``jax.devices()``) — interpreter
    and jax import time is identical cold or warm and is not what the
    AOT cache (or the watchdog's startup grace) is about."""
    import jax
    jax.devices()
    t0 = time.perf_counter()
    mod, batches = build_module()
    mod.fit_step(batches[0])
    ttfs = time.perf_counter() - t0
    from mxnet_tpu import aot_cache, profiler, telemetry
    # outside the timed region: background work (CPU twin serialization,
    # donated hot-swap compile) must land before this process exits or
    # the next attempt finds an empty cache
    aot_cache.drain(timeout=120)
    c = telemetry.report()["counters"]
    print(json.dumps({
        "ttfs_s": ttfs,
        "aot_hits": c.get("aot.cache_hits", 0),
        "aot_misses": c.get("aot.cache_misses", 0),
        "fit_step_compiles": profiler.step_stats()["compile_count"],
    }), flush=True)


def measure_ttfs():
    """Cold vs warm restart: two subprocesses sharing one cache dir —
    exactly what two launch.py restart attempts see."""
    cache = tempfile.mkdtemp(prefix="restart-probe-aot-")
    env = dict(os.environ)
    env.update({
        "MXTPU_AOT_CACHE_DIR": cache,
        "JAX_COMPILATION_CACHE_DIR": os.path.join(cache, "xla"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
        "JAX_PLATFORMS": "cpu",
    })
    out = {}
    try:
        for label in ("cold", "warm"):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--ttfs-child"],
                env=env, capture_output=True, text=True, timeout=600)
            if r.returncode != 0:
                raise RuntimeError("ttfs child (%s) failed rc=%d:\n%s"
                                   % (label, r.returncode,
                                      r.stderr[-2000:]))
            child = json.loads(r.stdout.strip().splitlines()[-1])
            out[label] = child
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    return {
        "cold_s": round(out["cold"]["ttfs_s"], 3),
        "warm_s": round(out["warm"]["ttfs_s"], 3),
        "speedup": round(out["cold"]["ttfs_s"] / out["warm"]["ttfs_s"], 2),
        "warm_aot_hits": out["warm"]["aot_hits"],
        "warm_fit_step_compiles": out["warm"]["fit_step_compiles"],
        "cold_fit_step_compiles": out["cold"]["fit_step_compiles"],
    }


def run():
    sync = measure_stalls("sync")
    async_ = measure_stalls("async")
    ttfs = measure_ttfs()
    return {
        "stall": {
            "sync": sync, "async": async_,
            "ratio_p50": round(sync["p50_ms"] / async_["p50_ms"], 2),
            "ratio_p99": round(sync["p99_ms"] / async_["p99_ms"], 2),
        },
        "ttfs": ttfs,
    }


if __name__ == "__main__":
    if "--ttfs-child" in sys.argv:
        _ttfs_child()
    else:
        print(json.dumps(run()))
