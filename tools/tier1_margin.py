#!/usr/bin/env python
"""Tier-1 wall-margin report (ISSUE 16): how many seconds of headroom
the tier-1 suite has left against the CI wall.

Usage::

    python tools/tier1_margin.py /tmp/_t1.log [--wall 870]

Parses the pytest summary line (``... in 743.21s (0:12:23) ...``) from
a captured tier-1 log and prints the wall, the suite's elapsed
seconds, and the remaining margin.  Exits 1 when the suite ran over
the wall (negative margin), 2 when no summary line is found (the run
died before pytest could report — e.g. the ``timeout`` harness killed
it), so CI can gate on shrinking headroom instead of discovering the
wall the hard way.

Robust to terminal wrapping: pytest folds its summary line under a
narrow ``COLUMNS`` (splitting ``in`` from ``743.21s``, or even the
digits from their trailing ``s``), which used to make this tool exit 2
on a run that DID report — scanning summary tokens across whitespace
and, failing that, rescanning with intra-line wraps collapsed keeps
the gate honest.
"""
import re
import sys

#: ``\s*`` (not a literal space) so a line wrap between ``in`` and the
#: seconds token still matches without any preprocessing
_SUMMARY = re.compile(r"\bin\s*(\d+(?:\.\d+)?)s\b")


def margin(log_text, wall=870.0):
    """Return ``(elapsed_s, margin_s)`` from the LAST pytest summary
    token in ``log_text``, or ``(None, None)`` when absent."""
    hits = _SUMMARY.findall(log_text)
    if not hits:
        # a wrap INSIDE the seconds token ("743.2\n1s") defeats any
        # line-aware scan — collapse intra-line wraps and rescan.
        # Joining lines cannot forge a summary token: ``\bin`` needs a
        # word boundary, so "with" + "in 5s" style joins don't match.
        hits = _SUMMARY.findall(
            re.sub(r"[ \t]*\n[ \t]*", "", log_text))
    if not hits:
        return None, None
    elapsed = float(hits[-1])
    return elapsed, wall - elapsed


def main(argv):
    args = [a for a in argv if not a.startswith("--")]
    wall = 870.0
    for a in argv:
        if a.startswith("--wall"):
            wall = float(a.split("=", 1)[1] if "=" in a
                         else argv[argv.index(a) + 1])
    if not args:
        sys.stderr.write(__doc__)
        return 2
    with open(args[0]) as f:
        text = f.read()
    elapsed, m = margin(text, wall)
    if elapsed is None:
        print("tier1-margin: no pytest summary line found in %s "
              "(run killed before reporting?)" % args[0])
        return 2
    print("tier1-margin: suite %.1fs, wall %.0fs, margin %+.1fs (%.0f%%"
          " of wall used)" % (elapsed, wall, m, 100.0 * elapsed / wall))
    return 1 if m < 0 else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
