#!/usr/bin/env python
"""Parse training logs into a markdown table.

Port of /root/reference/tools/parse_log.py: reads `Epoch[k] ...
Validation-accuracy=...` / `Train-accuracy=...` / `Time cost=...` lines
emitted by Module.fit and prints per-epoch train/val/time columns.
"""
from __future__ import annotations

import argparse
import re
import sys


def parse_log(lines, metric_name="accuracy"):
    """Returns dict epoch -> [train, val, time]."""
    res = [re.compile(r".*Epoch\[(\d+)\] Train-%s.*=([.\d]+)" % metric_name),
           re.compile(r".*Epoch\[(\d+)\] Validation-%s.*=([.\d]+)"
                      % metric_name),
           re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")]
    data = {}
    for line in lines:
        for i, pat in enumerate(res):
            m = pat.match(line)
            if m:
                epoch = int(m.groups()[0])
                val = float(m.groups()[1])
                if epoch not in data:
                    data[epoch] = [0.0] * len(res) * 2
                data[epoch][i * 2] += val
                data[epoch][i * 2 + 1] += 1
    return data


def format_table(data):
    out = ["| epoch | train-accuracy | valid-accuracy | time |",
           "| --- | --- | --- | --- |"]
    for k, v in sorted(data.items()):
        def cell(i):
            return "%.6f" % (v[i * 2] / v[i * 2 + 1]) if v[i * 2 + 1] else "-"
        out.append("| %d | %s | %s | %s |" % (k, cell(0), cell(1), cell(2)))
    return "\n".join(out)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Parse mxnet_tpu training logs")
    parser.add_argument("logfile", help="the log file for parsing")
    parser.add_argument("--format", default="markdown",
                        choices=["markdown", "none"])
    parser.add_argument("--metric-name", default="accuracy",
                        help="metric name in the log (e.g. accuracy)")
    args = parser.parse_args(argv)
    with open(args.logfile) as f:
        data = parse_log(f, args.metric_name)
    if args.format == "markdown":
        print(format_table(data))
    return data


if __name__ == "__main__":
    main()
