#!/usr/bin/env python
"""im2rec: pack an image folder / .lst into RecordIO (.rec + .idx).

Port of /root/reference/tools/im2rec.py (the C++ twin is tools/im2rec.cc).
Same CLI shape: `--list` generates prefix.lst from a root dir;
without --list, packs prefix.lst into prefix.rec/.idx with optional resize
+ JPEG re-encode; `--num-thread N` decodes in a thread pool (PIL codecs
release the GIL), playing the role of the reference's OpenMP threads.
"""
from __future__ import annotations

import argparse
import io as pyio
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def list_image(root, recursive, exts):
    """Yield (index, relpath, label) walking root (reference
    im2rec.py:list_image)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in os.walk(root, followlinks=True):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and (suffix in exts):
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
        for k, v in sorted(cat.items(), key=lambda x: x[1]):
            print(os.path.relpath(k, root), v)
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and (suffix in exts):
                yield (i, os.path.relpath(fpath, root), 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    N = len(image_list)
    chunk_size = (N + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        if not chunk:
            continue
        str_chunk = "_%d" % i if args.chunks > 1 else ""
        sep = int(len(chunk) * args.train_ratio)
        sep_test = int(len(chunk) * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + str_chunk + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + str_chunk + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + str_chunk + "_val.lst",
                           chunk[sep_test + sep:])
            write_list(args.prefix + str_chunk + "_train.lst",
                       chunk[sep_test:sep_test + sep])


def read_list(path_in):
    with open(path_in) as fin:
        while True:
            line = fin.readline()
            if not line:
                break
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                print("lst should have at least has three parts, but only "
                      "has %s parts for %s" % (line_len, line))
                continue
            try:
                item = [int(line[0])] + [line[-1]] + \
                    [float(i) for i in line[1:-1]]
            except Exception as e:
                print("Parsing lst met error for %s, detail: %s"
                      % (line, e))
                continue
            yield item


def image_encode(args, item):
    """Encode one list item; returns packed record bytes or None."""
    from mxnet_tpu import recordio
    fullpath = os.path.join(args.root, item[1])
    if len(item) > 3 and args.pack_label:
        header = recordio.IRHeader(0, item[2:], item[0], 0)
    else:
        header = recordio.IRHeader(0, item[2], item[0], 0)
    if args.pass_through:
        try:
            with open(fullpath, "rb") as fin:
                img = fin.read()
            return recordio.pack(header, img)
        except Exception as e:
            print("pack_img error:", item[1], e)
            return None
    try:
        from PIL import Image
        img = Image.open(fullpath)
        if args.color == 0:
            img = img.convert("L")
        elif args.color == 1:
            img = img.convert("RGB")
        # color == -1: keep the file's original channels (IMREAD_UNCHANGED)
        if args.resize:
            w, h = img.size
            if w > h:
                nh, nw = args.resize, int(w * args.resize / h)
            else:
                nh, nw = int(h * args.resize / w), args.resize
            img = img.resize((nw, nh), Image.BILINEAR)
        if args.center_crop:
            w, h = img.size
            s = min(w, h)
            img = img.crop(((w - s) // 2, (h - s) // 2,
                            (w + s) // 2, (h + s) // 2))
        buf = pyio.BytesIO()
        fmt = "JPEG" if args.encoding in (".jpg", ".jpeg") else "PNG"
        if fmt == "JPEG" and img.mode not in ("L", "RGB", "CMYK"):
            img = img.convert("RGB")  # JPEG can't hold alpha
        img.save(buf, format=fmt, quality=args.quality)
        return recordio.pack(header, buf.getvalue())
    except Exception as e:
        print("imread error trying to load file: %s; %s" % (fullpath, e))
        return None


def write_record(args, fname):
    from mxnet_tpu import recordio
    fname = os.path.basename(fname)
    fname_rec = os.path.splitext(fname)[0] + ".rec"
    fname_idx = os.path.splitext(fname)[0] + ".idx"
    working_dir = args.prefix if os.path.isdir(args.prefix) \
        else os.path.dirname(args.prefix)
    record = recordio.MXIndexedRecordIO(
        os.path.join(working_dir, fname_idx),
        os.path.join(working_dir, fname_rec), "w")
    image_list = list(read_list(os.path.join(working_dir, fname)
                                if not os.path.isabs(fname) else fname))
    cnt = 0
    pre_time = time.time()
    if args.num_thread > 1:
        # decode/encode in a thread pool (PIL releases the GIL for codec
        # work) — the reference's OpenMP parser role, tools/im2rec.cc
        from multiprocessing.pool import ThreadPool
        pool = ThreadPool(args.num_thread)
        encoded = pool.imap(lambda it: (it, image_encode(args, it)),
                            image_list, chunksize=8)
    else:
        encoded = ((it, image_encode(args, it)) for it in image_list)
    for item, s in encoded:
        if s is None:
            continue
        record.write_idx(item[0], s)
        if cnt % 1000 == 0 and cnt > 0:
            cur_time = time.time()
            print("time:", cur_time - pre_time, " count:", cnt)
            pre_time = cur_time
        cnt += 1
    if args.num_thread > 1:
        pool.close()
        pool.join()
    record.close()
    print("total", cnt, "records ->", fname_rec)


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="Create an image list or RecordIO file "
        "(reference tools/im2rec.py)")
    parser.add_argument("prefix", help="prefix of input/output lst+rec files")
    parser.add_argument("root", help="path to folder containing images")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="make a list instead of a record")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true")
    cgroup.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="skip transcoding, pack original bytes")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--num-thread", type=int, default=1)
    rgroup.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    rgroup.add_argument("--encoding", default=".jpg",
                        choices=[".jpg", ".png"])
    rgroup.add_argument("--pack-label", action="store_true")
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.list:
        make_list(args)
        return
    working_dir = args.prefix if os.path.isdir(args.prefix) \
        else os.path.dirname(args.prefix) or "."
    prefix_base = os.path.basename(args.prefix)
    files = [os.path.join(working_dir, f) for f in os.listdir(working_dir)
             if f.startswith(prefix_base) and f.endswith(".lst")]
    for fname in sorted(files):
        print("Creating .rec file from", fname, "in", working_dir)
        write_record(args, fname)


if __name__ == "__main__":
    main()
