#!/usr/bin/env python
"""Accelerator-vs-CPU operator consistency sweep.

The TPU analogue of the reference rerunning its whole operator suite on
GPU with ``check_consistency`` (/root/reference/tests/python/gpu/
test_operator_gpu.py, python/mxnet/test_utils.py:check_consistency):
every forward case from the numeric-gradient sweep
(tests/test_operator_grad_sweep.py) executes on the accelerator backend
AND on the XLA CPU backend, and the outputs must agree within per-dtype
tolerances.  This is what systematically checks that the lowerings the
CPU test suite validated produce the same numbers on the actual TPU.

Run as stage 6 of tools/tpu_validate.sh (JAX_PLATFORMS=axon).  On a
CPU-only host both sides use the same backend and the sweep degenerates
to a smoke check (noted in the output).

Usage: python tools/op_consistency.py  (OP_CONSISTENCY_DTYPES=... to
restrict dtypes).  Exit code: 0 = pass, 1 = any mismatch.
"""
from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TOLS = {  # per-dtype (rtol, atol), mirroring check_consistency's scaling
    "float32": (2e-5, 2e-5),
    "bfloat16": (2e-2, 2e-2),
}


def _load_sweep():
    path = os.path.join(REPO, "tests", "test_operator_grad_sweep.py")
    spec = importlib.util.spec_from_file_location("_grad_sweep", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import registry

    # lowering-semantics comparison: keep MXU matmuls in fp32 so a
    # mismatch means a wrong lowering, not accumulation-precision noise
    jax.config.update("jax_default_matmul_precision", "float32")

    dtypes = os.environ.get("OP_CONSISTENCY_DTYPES",
                            "float32,bfloat16").split(",")
    sweep = _load_sweep()
    accel = jax.devices()[0]
    cpu = jax.devices("cpu")[0]
    degenerate = accel.platform == "cpu"

    ran = skipped = 0
    failures = []
    for case in sweep.CASES:
        op = registry.get_op(case.op)
        if op.aux_names(case.params) or op.needs_rng or op.takes_train \
                or case.aux:
            skipped += 1  # stateful/rng ops: covered by their own tests
            continue
        params = dict(case.params)
        r = sweep.rng(0)
        raw = [sweep._sample(domain, shape, r)
               for _, shape, domain in case.inputs]
        for dt in dtypes:
            if dt == "bfloat16" and case.op.startswith("linalg_"):
                continue  # XLA decompositions (cholesky/trsm) are
                # fp32/fp64-only; bf16 linalg is not a supported path
            params_dt = params
            args = []
            for (name, _, domain), x in zip(case.inputs, raw):
                if name in case.fixed or domain.startswith("int"):
                    args.append(jnp.asarray(x, jnp.float32))
                else:
                    args.append(jnp.asarray(x.astype(np.float32), dt))
            fn = op.jitted(**op.canon_params(params_dt))
            try:
                with jax.default_device(accel):
                    out_a = fn(*[jax.device_put(a, accel) for a in args])
                with jax.default_device(cpu):
                    out_c = fn(*[jax.device_put(a, cpu) for a in args])
            except Exception as e:  # a backend refusing the case IS a finding
                failures.append((case.cid, dt, "raised: %r" % (e,)))
                continue
            flat_a = out_a if isinstance(out_a, (list, tuple)) else [out_a]
            flat_c = out_c if isinstance(out_c, (list, tuple)) else [out_c]
            rtol, atol = TOLS.get(dt, (2e-2, 2e-2))
            for i, (a, c) in enumerate(zip(flat_a, flat_c)):
                a = np.asarray(a, np.float64)
                c = np.asarray(c, np.float64)
                bad = ~np.isclose(a, c, rtol=rtol, atol=atol,
                                  equal_nan=True)
                if bad.any():
                    err = np.abs(a - c)[bad].max()
                    failures.append((case.cid, dt,
                                     "out%d max|Δ|=%.3g (%d/%d elems)"
                                     % (i, err, bad.sum(), bad.size)))
            ran += 1

    print("op_consistency: accel=%s cpu=%s cases_ran=%d skipped=%d "
          "dtypes=%s%s" % (accel.platform, cpu.platform, ran, skipped,
                           dtypes,
                           " [DEGENERATE: accel==cpu]" if degenerate
                           else ""))
    for cid, dt, msg in failures:
        print("  MISMATCH %s [%s]: %s" % (cid, dt, msg))
    if not failures:
        print("op_consistency: PASS")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
