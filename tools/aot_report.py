#!/usr/bin/env python
"""AOT cost/memory report — the fallback perf artifact when the TPU
tunnel is unavailable (VERDICT r3 next-round item 1).

For each headline workload the driver would time on hardware, this
lowers the EXACT jitted training step the benchmark runs and reports:

- the ANALYTIC FLOPs/step (the same formulas bench.py's MFU uses —
  the honest denominator),
- XLA's own HLO flop count as a crosscheck (CAVEAT: flops inside
  Pallas kernels are invisible to HLO cost analysis, and CPU-lowered
  "bytes accessed" reflects CPU fusion, not TPU — so no roofline is
  derived from it),
- projected v5e throughput at the efficiency levels the framework has
  actually MEASURED (PERF.md): pessimistic/measured/optimistic MFU.

Projections are scenarios, not measurements — PERF.md carries the real
numbers.  Run: python tools/aot_report.py  (writes PERF_AOT.md)
"""
from __future__ import annotations

import functools
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

V5E_PEAK_BF16 = 197e12     # dense bf16 FLOP/s

# per-workload: (last measured note, analytic flops/step, MFU scenarios)
def _resnet_flops(batch):
    return 3 * 2 * 4.089e9 * batch          # bench.py NETWORKS formula

def _attn_flops(b, h, t, d):
    return 3.5 * 4 * b * h * t * t * d / 2  # causal fwd+bwd

def _gpt_flops(batch, seq, n_layer=12, d_model=768, vocab=50304):
    n_matmul = n_layer * 12 * d_model * d_model + d_model * vocab
    return (6 * n_matmul * seq + n_layer * _attn_flops(1, 12, seq,
                                                       64)) * batch

MEASURED = {
    # MFU scenarios are on the ANALYTIC-flop basis used below: PERF.md's
    # 25.5% resnet row is XLA-flop basis (XLA counts ~8% under analytic,
    # bench.py note) — 2235 img/s on analytic 24.5 GFLOP/img is 27.8%
    "resnet50_bs128": ("2235 img/s, ~25.5% XLA-basis MFU (PERF.md r3)",
                       (0.20, 0.278, 0.32)),
    "flash_attention_fwd_bwd": ("fwd+bwd 39.4 TFLOP/s @T=4k / 58.4 "
                                "@T=32k, grid-streamed kernels "
                                "(PERF.md §7b, round 5)",
                                (0.15, 0.20, 0.30)),
    "gpt2_small_T2048": ("never measured (r5 headline run crashed "
                         "into a wedged tunnel, PERF.md §7b)",
                         (0.25, 0.35, 0.45)),
}


def _cost(lowered):
    try:
        ca = lowered.compile().cost_analysis()
    except Exception:
        ca = lowered.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca.get("flops", 0.0)), float(
        ca.get("bytes accessed", 0.0))


def resnet_step():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.block import functionalize

    batch = 128
    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    x0 = jnp.zeros((batch, 3, 224, 224), jnp.float32)
    fn, params = functionalize(net, x0, train=True)
    n_aux = fn.num_aux
    diff = params[:len(params) - n_aux]
    aux = params[len(params) - n_aux:]
    mom = [jnp.zeros_like(p) for p in diff]

    def loss_fn(diff, aux, x, y):
        cdiff = [p.astype(jnp.bfloat16) for p in diff]
        (logits,), new_aux = fn(cdiff + list(aux), x.astype(jnp.bfloat16))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean(), \
            new_aux

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(diff, aux, mom, x, y):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(diff, aux, x, y)
        new_mom = [0.9 * m - 0.05 * g.astype(jnp.float32)
                   for m, g in zip(mom, grads)]
        new_diff = [p + m for p, m in zip(diff, new_mom)]
        return new_diff, list(new_aux), new_mom, loss

    y = jnp.zeros((batch,), jnp.int32)
    return step.lower(diff, aux, mom, x0, y), batch, "img"


def attention_step():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    b, h, t, d = 4, 16, 4096, 128
    q = jnp.zeros((b, h, t, d), jnp.bfloat16)

    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, sum(x.astype(jnp.float32).sum() for x in g)

    return step.lower(q, q, q), b * h * t, "q-token"


def gpt2_step():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.gluon.model_zoo import gpt
    from mxnet_tpu.gluon.block import functionalize

    batch, seq, vocab = 8, 2048, 50304
    net = gpt.GPTLM(vocab, 12, 768, 12, max_len=seq)
    net.initialize()
    toks = jnp.zeros((batch, seq), jnp.int32)
    fn, params = functionalize(net, toks, train=True)
    mom = [jnp.zeros_like(p) for p in params]

    def loss_fn(ps, x, y):
        cps = [p.astype(jnp.bfloat16) for p in ps]
        (logits,), _ = fn(cps, x)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(ps, mom, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(ps, x, y)
        new_mom = [0.9 * m - 3e-4 * g.astype(jnp.float32)
                   for m, g in zip(mom, grads)]
        return [p + m for p, m in zip(ps, new_mom)], new_mom, loss

    return step.lower(params, mom, toks, toks), batch * seq, "token"


WORKLOADS = [
    ("resnet50_bs128", resnet_step, _resnet_flops(128)),
    ("flash_attention_fwd_bwd", attention_step,
     _attn_flops(4, 16, 4096, 128)),
    ("gpt2_small_T2048", gpt2_step, _gpt_flops(8, 2048)),
]


def main():
    rows = []
    for name, build, analytic in WORKLOADS:
        lowered, units, unit_name = build()
        xla_flops, _ = _cost(lowered)
        note, (lo, mid, hi) = MEASURED[name]
        row = {
            "workload": name,
            "analytic_flops_per_step": analytic,
            "xla_hlo_flops_per_step": xla_flops,
            "xla_vs_analytic": (xla_flops / analytic) if analytic else None,
            "unit": unit_name,
            "units_per_step": units,
            "last_measured": note,
        }
        for tag, mfu in (("pessimistic", lo), ("measured", mid),
                         ("optimistic", hi)):
            t = analytic / (mfu * V5E_PEAK_BF16)
            row["projected_%s" % tag] = {
                "mfu": mfu, "ms_per_step": round(t * 1e3, 2),
                "%s_per_sec" % unit_name: round(units / t, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    lines = [
        "# AOT cost report (tunnel-outage fallback artifact)",
        "",
        "The EXACT jitted benchmark steps, lowered ahead-of-time (proof",
        "they compile) with their analytic training FLOPs and projected",
        "v5e throughput at measured-efficiency scenarios.  XLA's HLO",
        "flop count is a crosscheck only: Pallas-kernel flops are",
        "invisible to it, and CPU-lowered byte counts reflect CPU",
        "fusion, so no roofline is derived.  PERF.md has the real",
        "measurements.  Regenerate: `python tools/aot_report.py`.",
        "",
        "| workload | analytic GFLOP/step | XLA/analytic | proj @low "
        "MFU | proj @measured MFU | proj @high MFU | last measured |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        u = r["unit"]

        def fmt(tag):
            p = r["projected_%s" % tag]
            return "%.0f %s/s @%.0f%%" % (p["%s_per_sec" % u], u,
                                          p["mfu"] * 100)
        lines.append(
            "| %s | %.1f | %.2f | %s | %s | %s | %s |"
            % (r["workload"], r["analytic_flops_per_step"] / 1e9,
               r["xla_vs_analytic"] or 0, fmt("pessimistic"),
               fmt("measured"), fmt("optimistic"), r["last_measured"]))
    lines.append("")
    with open(os.path.join(REPO, "PERF_AOT.md"), "w") as f:
        f.write("\n".join(lines))
    print("wrote PERF_AOT.md")


if __name__ == "__main__":
    main()
