#!/usr/bin/env python3
"""Convert a Jupyter notebook to markdown (reference tools/ipynb2md.py).

Dependency-free: walks the .ipynb JSON directly — markdown cells pass
through, code cells become fenced python blocks, text outputs become
plain fenced blocks.

Usage: python ipynb2md.py notebook.ipynb [-o notebook.md]
"""
from __future__ import annotations

import argparse
import json
import os


def convert(ipynb_path):
    with open(ipynb_path) as f:
        nb = json.load(f)
    lines = []
    for cell in nb.get("cells", []):
        src = "".join(cell.get("source", []))
        ctype = cell.get("cell_type")
        if ctype == "markdown":
            lines.append(src)
        elif ctype == "code":
            lines.append("```python\n%s\n```" % src.rstrip("\n"))
            outs = []
            for out in cell.get("outputs", []):
                if "text" in out:
                    outs.append("".join(out["text"]))
                elif "data" in out and "text/plain" in out["data"]:
                    outs.append("".join(out["data"]["text/plain"]))
            if outs:
                lines.append("```\n%s\n```" % "".join(outs).rstrip("\n"))
    return "\n\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("input")
    ap.add_argument("-o", "--output")
    args = ap.parse_args()
    out = args.output or os.path.splitext(args.input)[0] + ".md"
    md = convert(args.input)
    with open(out, "w") as f:
        f.write(md)
    print("wrote %s (%d bytes)" % (out, len(md)))


if __name__ == "__main__":
    main()
