#!/usr/bin/env python
"""One out-of-process serving replica: engine + RPC front-end.

The process shape of ISSUE 14: each ``ServingReplica`` runs in its own
OS process behind the length-framed JSON RPC plane
(``mxnet_tpu/serving/rpc.py``).  The main loop single-threadedly
interleaves RPC handling with the decode loop — the engine is never
touched from two threads:

    accept/answer pending RPCs  →  replica.step() when non-idle
    →  drain-on-request (exit 80)  →  repeat

Spin-up publishes a PORT FILE (``MXTPU_SERVE_PORT_FILE`` or
``--port-file``) carrying host/port/pid/attempt/boot-nonce — the
incarnation stamp router proxies pin, so a replacement taking over
the slot reads as confirmed death to the old proxy, never a silent
redirect.  The file is BOOTSTRAP DISCOVERY only (ISSUE 17): liveness
rides the ``heartbeat`` RPC (incarnation + decode-progress sequence),
and drain orders arrive as incarnation-authenticated ``drain`` RPCs —
the worker trusts no shared filesystem once it is up.  With
``MXTPU_AOT_CACHE_DIR`` exported (the ``tools/launch.py --serve``
default) a replacement spins up AOT-warm: 0 foreground serving
compiles before its first token (the health RPC reports the count).

Exit codes (the tools/launch.py contract):

- 80 — graceful drain (an RPC ``drain`` request, or SIGTERM): finish
  residents + accepted queue, verify page conservation, exit clean
  (never blamed toward eviction; the launcher journals
  drain/replace and respawns AOT-warm);
- 77 — replica lost (the ``serve.replica.lost`` site fired in a
  standalone process): retryable;
- 75 — a wedged decode (the stall watchdog's exit, armed via
  MXTPU_STALL_TIMEOUT);
- SIGKILL — the ``serve.replica.sigkill`` site (or the OOM killer):
  no cleanup runs, which is exactly what the fleet drill drills.

The model is built DETERMINISTICALLY from CLI args (seed + dims), so
every replica of a fleet serves bit-identical greedy tokens — the
failover re-decode contract.  ``--checkpoint-prefix`` additionally
subscribes the replica to a CheckpointManager prefix for live weight
hot-swap (PR 11).

Usage (typically under ``tools/launch.py --serve``):

    python tools/serve_worker.py --port-file /run/serve-port-slot0.json
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def build_net(args):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import gpt

    np.random.seed(args.seed)
    mx.random.seed(args.seed)
    net = gpt.GPTLM(args.vocab, args.n_layer, args.d_model, args.n_head,
                    max_len=args.max_len)
    net.initialize()
    return net


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="out-of-process serving replica (ISSUE 14)")
    parser.add_argument("--port-file",
                        default=os.environ.get("MXTPU_SERVE_PORT_FILE"),
                        help="where to publish host/port/pid/attempt "
                        "(MXTPU_SERVE_PORT_FILE; required)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="0 = pick a free port (the port file is "
                        "the discovery channel)")
    # deterministic model build — every replica of a fleet must serve
    # bit-identical greedy tokens
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--n-layer", type=int, default=2)
    parser.add_argument("--d-model", type=int, default=128)
    parser.add_argument("--n-head", type=int, default=4)
    parser.add_argument("--max-len", type=int, default=64)
    # engine shape
    parser.add_argument("--num-slots", type=int, default=8)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--max-prefill-len", type=int, default=32)
    parser.add_argument("--max-seq-len", type=int, default=48)
    parser.add_argument("--checkpoint-prefix", default=None,
                        help="subscribe to this CheckpointManager "
                        "prefix for live weight hot-swap")
    parser.add_argument("--idle-sleep", type=float, default=0.02,
                        help="idle RPC-poll timeout, seconds — the "
                        "only time the loop blocks (submit pickup "
                        "latency when idle; a serving loop polls "
                        "non-blocking)")
    parser.add_argument("--drain-linger", type=float, default=3.0,
                        help="seconds to keep answering status RPCs "
                        "after a drain completes, so router proxies "
                        "harvest the final request states before the "
                        "process exits 80")
    parser.add_argument("--max-seconds", type=float, default=0,
                        help="exit 0 after this long (test hygiene "
                        "backstop; 0 = run until drained/killed)")
    args = parser.parse_args(argv)
    if not args.port_file:
        parser.error("--port-file (or MXTPU_SERVE_PORT_FILE) required")

    # identity: under launch.py --serve the slot IS the rank (serving
    # has no collective world to re-pack)
    slot = os.environ.get("MXTPU_WORKER_SLOT",
                          os.environ.get("MXTPU_WORKER_RANK", "0"))
    attempt = int(os.environ.get("MXTPU_RESTART_ATTEMPT", "0") or 0)

    import jax
    jax.devices()   # backend up before the engine builds programs

    from mxnet_tpu import telemetry, watchdog
    from mxnet_tpu.serving import (CheckpointSubscriber, ReplicaLost,
                                   ServingEngine, ServingReplica)
    from mxnet_tpu.serving.rpc import RpcServer, write_port_file

    telemetry.install_crash_hooks()
    watchdog.start_heartbeat()      # no-op without MXTPU_HEARTBEAT_DIR
    watchdog.maybe_arm()            # no-op without MXTPU_STALL_TIMEOUT

    net = build_net(args)
    engine = ServingEngine(net, num_slots=args.num_slots,
                           page_size=args.page_size,
                           max_prefill_len=args.max_prefill_len,
                           max_seq_len=args.max_seq_len)
    subscriber = None
    if args.checkpoint_prefix:
        subscriber = CheckpointSubscriber(args.checkpoint_prefix, net)
    # the replica id names the INCARNATION: a replacement must not
    # inherit its corpse's tag, or serve_report's failover arcs would
    # read victim == survivor and the fleet view could never link a
    # re-decode that landed on the replaced slot (the proxy-side
    # successor naming, "slotK+attempt", matches this)
    rid = "slot%s" % slot if attempt == 0 else \
        "slot%s+%d" % (slot, attempt)
    replica = ServingReplica(engine, replica_id=rid,
                             subscriber=subscriber)
    # durable-before-discoverable: the engine's AOT variant stores run
    # in the background; a COLD worker must not publish its port file
    # (→ the fleet looks ready → a drill may kill a peer → the
    # launcher spawns a replacement) until its executables are on
    # disk, or the replacement races the store and pays a foreground
    # compile the warm-spin-up contract forbids
    from mxnet_tpu import aot_cache
    aot_cache.drain(timeout=180)
    server = RpcServer(replica, host=args.host, port=args.port,
                       attempt=attempt)
    # the port file repeats the server's OWN boot nonce: discovery and
    # the heartbeat RPC describe the same incarnation, so a proxy can
    # cross-check either channel without false mismatches
    write_port_file(args.port_file, server.port, host=args.host,
                    attempt=attempt,
                    nonce=server.incarnation["nonce"])
    print("serve_worker: slot %s attempt %d serving on %s:%d (pid %d "
          "nonce %s)"
          % (slot, attempt, args.host, server.port, os.getpid(),
             server.incarnation["nonce"]),
          file=sys.stderr, flush=True)

    # SIGTERM = polite drain request (the launcher teardown path): the
    # loop below notices and runs the full drain protocol → exit 80
    def _on_term(_sig, _frm):
        server.drain_requested = True
    signal.signal(signal.SIGTERM, _on_term)

    t_end = (time.monotonic() + args.max_seconds
             if args.max_seconds > 0 else None)
    rc = 0
    next_alert_t = 0.0
    try:
        while True:
            if t_end is not None and time.monotonic() > t_end:
                print("serve_worker: --max-seconds reached; exiting",
                      file=sys.stderr, flush=True)
                break
            idle = replica.idle
            server.poll(timeout=args.idle_sleep if idle else 0.0)
            if server.drain_requested:
                rc = replica.drain()
                # linger answering STATUS RPCs so router proxies can
                # harvest the drained requests' final states — exiting
                # on the ack would make the completions unobservable
                # and strand every in-flight handle "running"
                t_linger = time.monotonic() + args.drain_linger
                while time.monotonic() < t_linger:
                    server.poll(timeout=0.05)
                print("serve_worker: drained clean; exiting %d" % rc,
                      file=sys.stderr, flush=True)
                break
            if not replica.idle:
                replica.step()
            else:
                if subscriber is not None:
                    # an idle replica still hot-swaps publications
                    replica.maybe_swap()
                # idle alert cadence (ISSUE 18): replica.step() runs
                # the rules while decoding; an idle worker must still
                # notice its own stall/breaker state between pulls
                now = time.monotonic()
                if now >= next_alert_t:
                    next_alert_t = now + 1.0
                    telemetry.check_alerts(now)
                    # an idle worker still ages out retained stream
                    # buffers (ISSUE 19): step() runs this sweep while
                    # decoding, but terminal buffers past their TTL
                    # must not pin memory on a quiet replica
                    engine.sweep_streams()
    except ReplicaLost as e:
        # a standalone replica dies retryable — the launcher respawns
        # the slot and the router's proxy confirms the death
        print("serve_worker: %s — exiting retryable" % e,
              file=sys.stderr, flush=True)
        rc = 77
    finally:
        server.close()
        telemetry.stop_emitter()
        watchdog.stop_heartbeat()
    return rc


if __name__ == "__main__":
    sys.exit(main())
