#!/bin/bash
# TPU validation sequence after tunnel recovery. One process at a time,
# generous timeouts, NEVER kill mid-run.
set -x
cd /root/repo

# 1. new kernels at the standard shape (expect >= 36 TFLOP/s)
BENCH_MODE=attention BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1

# 2. long context: T=32k now compiles with grid-streamed kernels
BENCH_MODE=attention BENCH_ATTN_B=1 BENCH_ATTN_H=8 BENCH_ATTN_T=32768 \
  BENCH_STEPS=3 python bench.py 2>&1 | grep -v WARNING | tail -1

# 3. headline bench sanity
python bench.py 2>&1 | grep -v WARNING | tail -1

# 4. two more families for the per-network table
BENCH_NETWORK=resnet152_v1 BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1
BENCH_NETWORK=inception_v3 BENCH_STEPS=10 BENCH_BATCH=64 python bench.py 2>&1 | grep -v WARNING | tail -1
