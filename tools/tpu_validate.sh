#!/bin/bash
# TPU validation sequence after tunnel recovery. One process at a time,
# generous timeouts, NEVER kill mid-run.
set -x
cd /root/repo

# 1. new kernels at the standard shape (expect >= 36 TFLOP/s)
BENCH_MODE=attention BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1

# 1b. fused vs split backward A/B (round 4: the faster one becomes the
#     MXTPU_FLASH_BWD default).  NOTE: T=4k numbers alone must not crown
#     fused — its dq partials cost extra HBM (bounded at 1 GiB by
#     MXTPU_FLASH_BWD_DQ_BYTES chunking, round 5); check stage 2b's
#     long-T fused timing before flipping the default.
MXTPU_FLASH_BWD=fused BENCH_MODE=attention BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1

# 2. long context: T=32k now compiles with grid-streamed kernels
BENCH_MODE=attention BENCH_ATTN_B=1 BENCH_ATTN_H=8 BENCH_ATTN_T=32768 \
  BENCH_STEPS=3 python bench.py 2>&1 | grep -v WARNING | tail -1

# 2b. fused backward at T=32k: dq-partial chunking must hold it inside
#     the 1 GiB budget (pre-round-5 this shape wanted ~8.6 GB of
#     partials and would have OOMed)
MXTPU_FLASH_BWD=fused BENCH_MODE=attention BENCH_ATTN_B=1 BENCH_ATTN_H=8 \
  BENCH_ATTN_T=32768 BENCH_STEPS=3 python bench.py 2>&1 | grep -v WARNING | tail -1

# 3. headline bench sanity
python bench.py 2>&1 | grep -v WARNING | tail -1

# 4. transformer flagship MFU (round 4; expect the MFU headline here)
BENCH_MODE=transformer BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1
MXTPU_FLASH_BWD=fused BENCH_MODE=transformer BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1

# 4b. inference: prefill + KV-cache decode throughput (round 5)
BENCH_MODE=generate BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1

# 5. two more families for the per-network table
BENCH_NETWORK=resnet152_v1 BENCH_STEPS=10 python bench.py 2>&1 | grep -v WARNING | tail -1
BENCH_NETWORK=inception_v3 BENCH_STEPS=10 BENCH_BATCH=64 python bench.py 2>&1 | grep -v WARNING | tail -1

# 6. TPU-vs-CPU op consistency sweep (round 4)
python tools/op_consistency.py 2>&1 | tail -5
