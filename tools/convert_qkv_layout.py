#!/usr/bin/env python
"""Convert pre-round-4 FlashSelfAttention checkpoints to the head-major
fused-qkv layout.

Round 4 changed the fused qkv projection's out-dim ordering from
[3, H, D]-major to head-major [H, 3, D] (gluon/nn/basic_layers.py
FlashSelfAttention: a tensor-parallel column split then lands on whole
heads instead of straddling the q/k/v factor).  The tensor SHAPE
(3C, in) is unchanged, so an old checkpoint loads without error but
permutes q/k/v slices across heads — wrong attention with no
diagnostic.  The layouts cannot be told apart from the file alone;
run this once over any V2 ``.params`` file saved by a round-3 build:

    python tools/convert_qkv_layout.py --num-heads 12 old.params new.params

Every parameter whose name ends in ``qkv_weight`` / ``qkv_bias`` has
its out dim re-ordered (3, H, D) -> (H, 3, D); everything else is
copied through byte-identical.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def convert_qkv(arr, num_heads):
    """Re-order the out dim of a fused qkv weight/bias from [3, H, D]
    to head-major [H, 3, D].  arr: numpy [3C] or [3C, in]."""
    import numpy as np
    a = np.asarray(arr)
    three_c = a.shape[0]
    if three_c % (3 * num_heads):
        raise ValueError("out dim %d not divisible by 3*heads=%d"
                         % (three_c, 3 * num_heads))
    d = three_c // (3 * num_heads)
    rest = a.shape[1:]
    return a.reshape((3, num_heads, d) + rest) \
            .transpose((1, 0, 2) + tuple(range(3, 3 + len(rest)))) \
            .reshape(a.shape)


def convert_file(src, dst, num_heads):
    from mxnet_tpu import ndarray as nd
    loaded = nd.load(src)
    if not isinstance(loaded, dict):
        raise SystemExit("expected a name-keyed .params file")
    out, converted = {}, []
    for name, arr in loaded.items():
        if name.endswith("qkv_weight") or name.endswith("qkv_bias"):
            out[name] = nd.array(convert_qkv(arr.asnumpy(), num_heads))
            converted.append(name)
        else:
            out[name] = arr
    nd.save(dst, out)
    return converted


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src", help="round-3 .params file ([3,H,D] layout)")
    ap.add_argument("dst", help="output .params file ([H,3,D] layout)")
    ap.add_argument("--num-heads", type=int, required=True,
                    help="attention heads of every qkv layer in the file")
    args = ap.parse_args(argv)
    converted = convert_file(args.src, args.dst, args.num_heads)
    print("converted %d qkv parameter(s): %s"
          % (len(converted), ", ".join(converted) or "(none)"))


if __name__ == "__main__":
    main()
