/*
 * Header-only C++ wrapper over the C predict ABI (c_predict_api.h) —
 * the analogue of the reference's cpp-package for the deployment path:
 * RAII handle ownership, std::vector IO, exceptions instead of return
 * codes.
 *
 *   mxtpu::Predictor pred(symbol_json, param_blob,
 *                         {{"data", {1, 3, 224, 224}}});
 *   pred.SetInput("data", pixels);
 *   pred.Forward();
 *   std::vector<float> probs = pred.GetOutput(0);
 */
#ifndef MXTPU_PREDICTOR_HPP_
#define MXTPU_PREDICTOR_HPP_

#include <cstdint>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "c_predict_api.h"

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string &what) : std::runtime_error(what) {}
};

namespace detail {
inline void check(int rc, const char *call) {
  if (rc != 0) {
    throw Error(std::string(call) + ": " + MXPredGetLastError());
  }
}
}  // namespace detail

class Predictor {
 public:
  using Shape = std::vector<mxt_uint>;
  using NamedShapes = std::vector<std::pair<std::string, Shape>>;

  enum DevType { kCPU = 1, kAccelerator = 2 };

  Predictor(const std::string &symbol_json, const std::string &param_bytes,
            const NamedShapes &input_shapes, int dev_type = kCPU,
            int dev_id = 0) {
    std::vector<const char *> keys;
    std::vector<mxt_uint> indptr;
    std::vector<mxt_uint> data;
    PackShapes(input_shapes, &keys, &indptr, &data);
    detail::check(
        MXPredCreate(symbol_json.c_str(), param_bytes.data(),
                     static_cast<int>(param_bytes.size()), dev_type, dev_id,
                     static_cast<mxt_uint>(keys.size()), keys.data(),
                     indptr.data(), data.data(), &handle_),
        "MXPredCreate");
  }

  Predictor(const Predictor &) = delete;
  Predictor &operator=(const Predictor &) = delete;

  Predictor(Predictor &&other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }

  ~Predictor() {
    if (handle_ != nullptr) MXPredFree(handle_);
  }

  void SetInput(const std::string &key, const std::vector<float> &values) {
    detail::check(
        MXPredSetInput(handle_, key.c_str(), values.data(),
                       static_cast<mxt_uint>(values.size())),
        "MXPredSetInput");
  }

  void Forward() { detail::check(MXPredForward(handle_), "MXPredForward"); }

  Shape GetOutputShape(mxt_uint index = 0) const {
    mxt_uint *dims = nullptr;
    mxt_uint ndim = 0;
    detail::check(MXPredGetOutputShape(handle_, index, &dims, &ndim),
                  "MXPredGetOutputShape");
    return Shape(dims, dims + ndim);
  }

  std::vector<float> GetOutput(mxt_uint index = 0) const {
    Shape shape = GetOutputShape(index);
    mxt_uint size = std::accumulate(shape.begin(), shape.end(), mxt_uint(1),
                                    std::multiplies<mxt_uint>());
    std::vector<float> out(size);
    detail::check(MXPredGetOutput(handle_, index, out.data(), size),
                  "MXPredGetOutput");
    return out;
  }

 private:
  explicit Predictor(PredictorHandle h) : handle_(h) {}

 public:
  /* A NEW predictor for new input shapes; this one stays usable. */
  Predictor Reshape(const NamedShapes &input_shapes) const {
    std::vector<const char *> keys;
    std::vector<mxt_uint> indptr;
    std::vector<mxt_uint> data;
    PackShapes(input_shapes, &keys, &indptr, &data);
    PredictorHandle out = nullptr;
    detail::check(
        MXPredReshape(static_cast<mxt_uint>(keys.size()), keys.data(),
                      indptr.data(), data.data(), handle_, &out),
        "MXPredReshape");
    return Predictor(out);
  }

 private:
  /* NamedShapes -> the C ABI's (keys, CSR indptr, flat dims) triple.
   * The key c_str pointers borrow from input_shapes — keep it alive. */
  static void PackShapes(const NamedShapes &input_shapes,
                         std::vector<const char *> *keys,
                         std::vector<mxt_uint> *indptr,
                         std::vector<mxt_uint> *data) {
    indptr->push_back(0);
    for (const auto &kv : input_shapes) {
      keys->push_back(kv.first.c_str());
      data->insert(data->end(), kv.second.begin(), kv.second.end());
      indptr->push_back(static_cast<mxt_uint>(data->size()));
    }
  }

  PredictorHandle handle_ = nullptr;
};

}  // namespace mxtpu

#endif  // MXTPU_PREDICTOR_HPP_
