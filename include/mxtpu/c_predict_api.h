/*
 * C predict ABI for the TPU-native framework.
 *
 * Shape-compatible with the reference inference surface
 * (reference include/mxnet/c_predict_api.h: MXPredCreate /
 * MXPredCreatePartialOut / MXPredGetOutputShape / MXPredSetInput /
 * MXPredForward / MXPredGetOutput / MXPredFree) so C/C++/FFI serving
 * stacks written against it recompile against this header.  The
 * implementation (src/mxtpu/c_predict_api.cc) drives the framework's
 * Predictor through CPython: embedded when the caller is a plain C
 * process, attached via the GIL when loaded into an existing Python
 * process.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef uint32_t mxt_uint;
typedef void *PredictorHandle;

/* Last error message for the calling thread ("" when none). */
const char *MXPredGetLastError(void);

/*
 * Create a predictor from a symbol JSON string and a parameter blob
 * (the bytes of a `prefix-0000.params` file, reference V2 binary or
 * npz).  Input shapes arrive CSR-style: input_shape_indptr has
 * num_input_nodes+1 entries delimiting each input's dims inside
 * input_shape_data.
 * dev_type: 1 = cpu, 2 = gpu (mapped to the accelerator), per the
 * reference's enum; dev_id selects the device.
 * Returns 0 on success, -1 on failure (see MXPredGetLastError).
 */
int MXPredCreate(const char *symbol_json_str,
                 const void *param_bytes,
                 int param_size,
                 int dev_type, int dev_id,
                 mxt_uint num_input_nodes,
                 const char **input_keys,
                 const mxt_uint *input_shape_indptr,
                 const mxt_uint *input_shape_data,
                 PredictorHandle *out);

/* Same, but the outputs are the named internal layers (e.g. a feature
 * layer for extraction) instead of the symbol's heads. */
int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes,
                           int param_size,
                           int dev_type, int dev_id,
                           mxt_uint num_input_nodes,
                           const char **input_keys,
                           const mxt_uint *input_shape_indptr,
                           const mxt_uint *input_shape_data,
                           mxt_uint num_output_nodes,
                           const char **output_keys,
                           PredictorHandle *out);

/* Output `index`'s shape; *shape_data stays owned by the predictor and
 * is valid until the next call on the same handle. */
int MXPredGetOutputShape(PredictorHandle handle,
                         mxt_uint index,
                         mxt_uint **shape_data,
                         mxt_uint *shape_ndim);

/* Stage `size` floats for the named input. */
int MXPredSetInput(PredictorHandle handle,
                   const char *key,
                   const float *data,
                   mxt_uint size);

/* Run the compiled forward program on the staged inputs. */
int MXPredForward(PredictorHandle handle);

/* Copy output `index` into data (size = element count, must match). */
int MXPredGetOutput(PredictorHandle handle,
                    mxt_uint index,
                    float *data,
                    mxt_uint size);

/* Rebind for new input shapes, keeping the loaded weights. */
int MXPredReshape(mxt_uint num_input_nodes,
                  const char **input_keys,
                  const mxt_uint *input_shape_indptr,
                  const mxt_uint *input_shape_data,
                  PredictorHandle handle,
                  PredictorHandle *out);

int MXPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
